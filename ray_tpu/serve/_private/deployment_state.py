"""Deployment replica rollout state machine.

Reference: python/ray/serve/_private/deployment_state.py —
DeploymentState (:1226) reconciles target config vs live replicas
(DeploymentReplica :879): scale up/down, rolling update on version change,
health checking, graceful stop. Runs inside the controller's control loop.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import time
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.core import serialization as ser
from ray_tpu.serve.config import DeploymentConfig
from ray_tpu.serve._private.common import (
    DeploymentID, DeploymentStatus, DeploymentStatusInfo, ReplicaState,
    RunningReplicaInfo, SERVE_NAMESPACE, format_replica_actor_name)

logger = logging.getLogger(__name__)


class DeploymentVersion:
    """Code + user_config hash; a change triggers rolling update
    (reference deployment_state.py DeploymentVersion)."""

    @staticmethod
    def compute(serialized_def: bytes, config: DeploymentConfig) -> str:
        if config.version:
            return config.version
        h = hashlib.sha1(serialized_def)
        h.update(repr(config.user_config).encode())
        return h.hexdigest()[:16]


class DeploymentReplica:
    """Tracks one replica actor through STARTING → RUNNING → STOPPING."""

    _counter = 0

    def __init__(self, deployment_id: DeploymentID, version: str):
        DeploymentReplica._counter += 1
        # Random suffix keeps replica names unique across controller
        # restarts: a recovered controller's counter restarts at 1 while
        # detached replicas from the previous incarnation still hold their
        # names in the GCS.
        uid = f"{DeploymentReplica._counter:05d}-{os.urandom(3).hex()}"
        self.replica_id = f"{deployment_id.name}#{uid}"
        self.actor_name = format_replica_actor_name(deployment_id, uid)
        self.deployment_id = deployment_id
        self.version = version
        self.state = ReplicaState.STARTING
        self.handle = None
        self.ready_ref = None
        self.stop_ref = None
        self.node_id = ""  # learned when RUNNING; for locality routing
        self.last_health_check: float = time.time()
        self.health_ref = None
        self.num_ongoing: int = 0
        self.custom_metric = None  # user autoscaling metric (polled)

    def start(self, serialized_def: bytes, init_args_blob: bytes,
              config: DeploymentConfig) -> None:
        from ray_tpu.serve._private.replica import ReplicaActor

        actor_options = dict(config.ray_actor_options)
        actor_options.update(
            name=self.actor_name,
            namespace=SERVE_NAMESPACE,
            lifetime="detached",
            max_concurrency=max(config.max_ongoing_requests * 2, 16),
        )
        self.handle = ReplicaActor.options(**actor_options).remote(
            self.replica_id, self.deployment_id.name,
            self.deployment_id.app_name, serialized_def, init_args_blob,
            config.to_dict())
        # First call resolves once __init__ finished.
        self.ready_ref = self.handle.get_metadata.remote()

    def check_started(self) -> Optional[bool]:
        """True=ready, False=failed, None=still starting."""
        if self.ready_ref is None:
            return True
        done, _ = ray_tpu.wait([self.ready_ref], timeout=0)
        if not done:
            return None
        try:
            ray_tpu.get(self.ready_ref)
            self.ready_ref = None
            self.state = ReplicaState.RUNNING
            try:
                from ray_tpu._private.worker import global_worker

                view = global_worker().gcs_call("get_actor_info", {
                    "actor_id": self.handle._actor_id.binary()})
                nid = (view or {}).get("node_id")
                self.node_id = nid.hex() if nid else ""
            except Exception:
                self.node_id = ""
            return True
        except Exception as e:
            logger.error("replica %s failed to start: %s", self.replica_id, e)
            return False

    def begin_stop(self, timeout_s: float) -> None:
        self.state = ReplicaState.STOPPING
        if self.handle is not None:
            try:
                self.stop_ref = self.handle.prepare_for_shutdown.remote(
                    timeout_s)
            except Exception:
                self.stop_ref = None

    def check_stopped(self) -> bool:
        if self.handle is None:
            return True
        if self.stop_ref is not None:
            done, _ = ray_tpu.wait([self.stop_ref], timeout=0)
            if not done:
                return False
            self.stop_ref = None
        try:
            ray_tpu.kill(self.handle)
        except Exception:
            pass
        self.handle = None
        return True

    def running_info(self, config: DeploymentConfig) -> RunningReplicaInfo:
        return RunningReplicaInfo(
            replica_id=self.replica_id,
            actor_name=self.actor_name,
            deployment=self.deployment_id.name,
            app_name=self.deployment_id.app_name,
            max_ongoing_requests=config.max_ongoing_requests,
            node_id=self.node_id)


class DeploymentState:
    def __init__(self, deployment_id: DeploymentID,
                 on_running_replicas_changed):
        self.deployment_id = deployment_id
        self.target_config: Optional[DeploymentConfig] = None
        self.target_version: Optional[str] = None
        self.target_num_replicas: int = 0
        self.serialized_def: bytes = b""
        self.init_args_blob: bytes = ser.dumps(((), {}))
        self.replicas: List[DeploymentReplica] = []
        self.deleting = False
        self.message = ""
        self._on_running_changed = on_running_replicas_changed
        self._last_broadcast: Optional[list] = None
        self._consecutive_start_failures = 0

    # ------------------------------------------------------------- targets
    def deploy(self, serialized_def: bytes, init_args_blob: bytes,
               config: DeploymentConfig) -> None:
        version = DeploymentVersion.compute(serialized_def, config)
        self.serialized_def = serialized_def
        self.init_args_blob = init_args_blob
        self.target_config = config
        self.target_version = version
        self.deleting = False
        if config.autoscaling_config is not None:
            ac = config.autoscaling_config
            current = self.target_num_replicas or (
                ac.initial_replicas if ac.initial_replicas is not None
                else ac.min_replicas)
            self.target_num_replicas = min(max(current, ac.min_replicas),
                                           ac.max_replicas)
        else:
            self.target_num_replicas = config.num_replicas

    def set_target_num_replicas(self, n: int) -> None:
        self.target_num_replicas = n

    def delete(self) -> None:
        self.deleting = True
        self.target_num_replicas = 0

    # ------------------------------------------------------------ reconcile
    def reconcile(self) -> None:
        """One pass of the rollout state machine. Driven by the controller
        loop (reference deployment_state.py update())."""
        cfg = self.target_config
        if cfg is None:
            return
        # 1. Reap stopping replicas.
        self.replicas = [
            r for r in self.replicas
            if not (r.state == ReplicaState.STOPPING and r.check_stopped())]
        # 2. Promote started replicas; drop failed starts.
        alive: List[DeploymentReplica] = []
        for r in self.replicas:
            if r.state == ReplicaState.STARTING:
                status = r.check_started()
                if status is False:
                    self._consecutive_start_failures += 1
                    r.begin_stop(0)
                    r.check_stopped()
                    continue
                if status is True:
                    self._consecutive_start_failures = 0
            alive.append(r)
        self.replicas = alive
        # 3. Rolling update with surge: new-version replicas are started
        #    first (stale ones don't count toward target in step 4); a stale
        #    replica is only stopped once a new-version replica is RUNNING
        #    to take its place, so serving capacity never drops to zero.
        stale_running = [r for r in self.replicas
                         if r.state == ReplicaState.RUNNING
                         and r.version != self.target_version]
        new_running = sum(1 for r in self.replicas
                          if r.state == ReplicaState.RUNNING
                          and r.version == self.target_version)
        for r in stale_running[:new_running]:
            r.begin_stop(cfg.graceful_shutdown_timeout_s)
        # 4. Scale to target (counting only target-version replicas).
        active = [r for r in self.replicas
                  if r.state in (ReplicaState.STARTING, ReplicaState.RUNNING)
                  and r.version == self.target_version]
        delta = self.target_num_replicas - len(active)
        if delta > 0 and self._consecutive_start_failures < 3:
            for _ in range(delta):
                rep = DeploymentReplica(self.deployment_id,
                                        self.target_version)
                try:
                    rep.start(self.serialized_def, self.init_args_blob, cfg)
                    self.replicas.append(rep)
                except Exception as e:
                    logger.error("failed to start replica: %s", e)
                    self._consecutive_start_failures += 1
                    break
        elif delta < 0:
            # Stop the newest non-running first, then excess running ones.
            excess = sorted(
                active, key=lambda r: r.state == ReplicaState.RUNNING)
            for r in excess[:-delta]:
                r.begin_stop(cfg.graceful_shutdown_timeout_s)
        self._broadcast_running()

    def check_health(self) -> None:
        """Kick/collect health checks on RUNNING replicas; replace dead
        ones (reference: replica health_check in deployment_state.py)."""
        cfg = self.target_config
        if cfg is None:
            return
        now = time.time()
        for r in list(self.replicas):
            if r.state != ReplicaState.RUNNING:
                continue
            if r.health_ref is not None:
                done, _ = ray_tpu.wait([r.health_ref], timeout=0)
                if done:
                    try:
                        ray_tpu.get(r.health_ref)
                        r.last_health_check = now
                    except Exception as e:
                        logger.warning("replica %s unhealthy: %s",
                                       r.replica_id, e)
                        r.begin_stop(0)
                    r.health_ref = None
                elif now - r.last_health_check > cfg.health_check_timeout_s:
                    logger.warning("replica %s health check timed out",
                                   r.replica_id)
                    r.health_ref = None
                    r.begin_stop(0)
            elif now - r.last_health_check > cfg.health_check_period_s:
                try:
                    r.health_ref = r.handle.check_health.remote()
                except Exception:
                    r.begin_stop(0)
        self._broadcast_running()

    def collect_autoscaling_stats(self, custom: bool = False) -> None:
        """Refresh per-replica ongoing-request counts (best effort);
        with custom=True also pull the user-recorded autoscaling
        metric (serve.metrics.record_autoscaling_metric)."""
        if custom:
            crefs, creps = [], []
            for r in self.replicas:
                if r.state == ReplicaState.RUNNING and r.handle is not None:
                    try:
                        crefs.append(
                            r.handle.get_autoscaling_metric.remote())
                        creps.append(r)
                    except Exception:
                        pass
            if crefs:
                cdone, _ = ray_tpu.wait(crefs, num_returns=len(crefs),
                                        timeout=2.0)
                for r, ref in zip(creps, crefs):
                    if ref in cdone:
                        try:
                            r.custom_metric = ray_tpu.get(ref)
                        except Exception:
                            pass
        refs, reps = [], []
        for r in self.replicas:
            if r.state == ReplicaState.RUNNING and r.handle is not None:
                try:
                    refs.append(r.handle.get_num_ongoing_requests.remote())
                    reps.append(r)
                except Exception:
                    pass
        if not refs:
            return
        done, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=2.0)
        for r, ref in zip(reps, refs):
            if ref in done:
                try:
                    r.num_ongoing = ray_tpu.get(ref)
                except Exception:
                    pass

    def total_ongoing_requests(self) -> float:
        return float(sum(r.num_ongoing for r in self.replicas
                         if r.state == ReplicaState.RUNNING))

    def total_custom_metric(self) -> float:
        """Sum of the replicas' user-recorded autoscaling values
        (replicas that never recorded count as 0)."""
        return float(sum(getattr(r, "custom_metric", None) or 0.0
                         for r in self.replicas
                         if r.state == ReplicaState.RUNNING))

    # ------------------------------------------------------------- queries
    def running_replica_infos(self) -> List[dict]:
        cfg = self.target_config
        return [r.running_info(cfg).to_dict() for r in self.replicas
                if r.state == ReplicaState.RUNNING]

    def _broadcast_running(self) -> None:
        infos = self.running_replica_infos()
        if infos != self._last_broadcast:
            self._last_broadcast = infos
            self._on_running_changed(self.deployment_id, infos)

    def curr_status_info(self) -> DeploymentStatusInfo:
        counts: Dict[str, int] = {}
        for r in self.replicas:
            counts[r.state.value] = counts.get(r.state.value, 0) + 1
        # Only current-version replicas count toward readiness: during a
        # rollout the surviving stale replicas keep serving, but the deploy
        # is not HEALTHY until the new version reaches target scale.
        running = sum(1 for r in self.replicas
                      if r.state == ReplicaState.RUNNING and
                      r.version == self.target_version)
        if self._consecutive_start_failures >= 3:
            status = DeploymentStatus.UNHEALTHY
            msg = "replicas failed to start 3 times in a row"
        elif running < self.target_num_replicas:
            status = DeploymentStatus.UPDATING
            msg = (f"{running}/{self.target_num_replicas} replicas running")
        else:
            status = DeploymentStatus.HEALTHY
            msg = ""
        return DeploymentStatusInfo(
            name=self.deployment_id.name, status=status, message=msg,
            replica_states=counts)

    def is_deleted(self) -> bool:
        return self.deleting and not self.replicas


class DeploymentStateManager:
    def __init__(self, on_running_replicas_changed):
        self._states: Dict[DeploymentID, DeploymentState] = {}
        self._on_running_changed = on_running_replicas_changed

    def deploy(self, deployment_id: DeploymentID, serialized_def: bytes,
               init_args_blob: bytes, config: DeploymentConfig) -> None:
        if deployment_id not in self._states:
            self._states[deployment_id] = DeploymentState(
                deployment_id, self._on_running_changed)
        self._states[deployment_id].deploy(serialized_def, init_args_blob,
                                           config)

    def delete(self, deployment_id: DeploymentID) -> None:
        if deployment_id in self._states:
            self._states[deployment_id].delete()

    def get(self, deployment_id: DeploymentID) -> Optional[DeploymentState]:
        return self._states.get(deployment_id)

    def states_for_app(self, app_name: str) -> List[DeploymentState]:
        return [s for d, s in self._states.items() if d.app_name == app_name]

    def reconcile_all(self) -> None:
        for state in list(self._states.values()):
            state.reconcile()
            state.check_health()
        for did in [d for d, s in self._states.items() if s.is_deleted()]:
            del self._states[did]

    def all_states(self) -> Dict[DeploymentID, DeploymentState]:
        return dict(self._states)
