"""Versioned wire schema of the Serve rpc ingress.

Reference: src/ray/protobuf/serve.proto + the gRPCProxy
(python/ray/serve/_private/proxy.py:540) — an externally-consumable,
versioned request/response contract. The transport is the framework's
length-prefixed msgpack framing (core/rpc.py); messages here define the
`serve_call` method's payload, exactly as a .proto would:

    frame     := u32 little-endian length | msgpack body
    request   := [REQUEST=0, msgid:u64, "serve_call", ServeCallRequest]
    response  := [RESPONSE=1, msgid:u64, ServeCallResponse]
    error     := [ERROR=2, msgid:u64, message:str]

Schema evolution: ``schema_version`` is carried in every message.
Servers accept any REQUEST version <= SCHEMA_VERSION, default missing
fields, and ignore unknown fields (msgpack maps) — so v1 clients keep
working against newer proxies. Responses are always the v1 envelope
(status/result/error/request_id); clients must tolerate added response
fields in future versions.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

SCHEMA_VERSION = 1

# Response status codes (proto-style enum).
STATUS_OK = 0
STATUS_APP_ERROR = 1        # user code raised
STATUS_NOT_FOUND = 2        # unknown app/deployment
STATUS_TIMEOUT = 3
STATUS_INVALID = 4          # malformed request


@dataclass
class ServeCallRequest:
    """serve_call request body (map on the wire)."""

    app: str = "default"
    deployment: Optional[str] = None      # None → the app's ingress
    method: Optional[str] = None          # None → __call__
    payload: Any = None
    multiplexed_model_id: str = ""
    request_id: str = ""
    schema_version: int = SCHEMA_VERSION

    def to_wire(self) -> Dict[str, Any]:
        return {
            "schema_version": self.schema_version,
            "app": self.app,
            "deployment": self.deployment,
            "method": self.method,
            "payload": self.payload,
            "multiplexed_model_id": self.multiplexed_model_id,
            "request_id": self.request_id,
        }

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "ServeCallRequest":
        if not isinstance(d, dict):
            raise SchemaError(f"request body must be a map, got "
                              f"{type(d).__name__}")
        version = d.get("schema_version", 1)
        if not isinstance(version, int) or version < 1:
            raise SchemaError(f"bad schema_version {version!r}")
        if version > SCHEMA_VERSION:
            raise SchemaError(
                f"request schema_version {version} is newer than this "
                f"server's {SCHEMA_VERSION}")
        app = d.get("app", "default")
        if not isinstance(app, str):
            raise SchemaError("'app' must be a string")
        dep = d.get("deployment")
        if dep is not None and not isinstance(dep, str):
            raise SchemaError("'deployment' must be a string or null")
        method = d.get("method")
        if method is not None and not isinstance(method, str):
            raise SchemaError("'method' must be a string or null")
        return cls(app=app, deployment=dep, method=method,
                   payload=d.get("payload"),
                   multiplexed_model_id=d.get("multiplexed_model_id", ""),
                   request_id=d.get("request_id", ""),
                   schema_version=version)


@dataclass
class ServeCallResponse:
    """serve_call response body (map on the wire)."""

    status: int = STATUS_OK
    result: Any = None
    error: str = ""
    request_id: str = ""
    schema_version: int = SCHEMA_VERSION

    def to_wire(self) -> Dict[str, Any]:
        return {
            "schema_version": self.schema_version,
            "status": self.status,
            "result": self.result,
            "error": self.error,
            "request_id": self.request_id,
        }

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "ServeCallResponse":
        if not isinstance(d, dict):
            raise SchemaError("response body must be a map")
        return cls(status=d.get("status", STATUS_OK),
                   result=d.get("result"),
                   error=d.get("error", ""),
                   request_id=d.get("request_id", ""),
                   schema_version=d.get("schema_version", 1))


class SchemaError(ValueError):
    """Malformed or incompatible ingress message."""
