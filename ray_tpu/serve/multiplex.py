"""@serve.multiplexed — per-replica LRU of loaded models.

Reference: python/ray/serve/multiplex.py (_ModelMultiplexWrapper) +
serve.get_multiplexed_model_id. A replica loads up to max_num_models_per_
replica models on demand and evicts least-recently-used; the router
prefers replicas that already hold the requested model.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
from collections import OrderedDict
from typing import Callable, Optional


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3,
                on_evict: Optional[Callable] = None):
    """Decorator: per-replica LRU cache over a model loader.

    ``on_evict(model_id, model)`` is called synchronously whenever the
    LRU drops a model — the seam that keeps EXTERNAL residency ledgers
    (e.g. a DecodeEngine AdapterPool whose adapter table mirrors the
    multiplex cache) consistent with the wrapper's own records: the
    router's multiplexed-model advertisement and the adapter pool
    must never disagree about what this replica holds. Callback
    exceptions are swallowed (an eviction must never fail the request
    that triggered it)."""
    def wrap(fn):
        caches = {}

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                owner, model_id = args
                bound = functools.partial(fn, owner)
                key = id(owner)
            else:
                (model_id,) = args
                owner, bound, key = None, fn, None
            cache = caches.get(key)
            if cache is None:
                cache = caches[key] = OrderedDict()
            if model_id in cache:
                cache.move_to_end(model_id)
                return cache[model_id]
            if inspect.iscoroutinefunction(fn):
                model = await bound(model_id)
            else:
                model = await asyncio.get_running_loop().run_in_executor(
                    None, bound, model_id)
            cache[model_id] = model
            _record_model(model_id)
            while len(cache) > max_num_models_per_replica:
                evicted_id, evicted = cache.popitem(last=False)
                _unrecord_model(evicted_id)
                if on_evict is not None:
                    try:
                        on_evict(evicted_id, evicted)
                    except Exception:
                        pass
            return model

        wrapper._is_serve_multiplexed = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


def _record_model(model_id: str) -> None:
    """Advertise the loaded model on this replica so the router can route
    matching requests here."""
    try:
        from ray_tpu.serve._private import replica as replica_mod

        actor = replica_mod._current_replica
        if actor is not None:
            actor.record_multiplexed_model(model_id)
    except Exception:
        pass


def _unrecord_model(model_id: str) -> None:
    try:
        from ray_tpu.serve._private import replica as replica_mod

        actor = replica_mod._current_replica
        if actor is not None and \
                model_id in actor._multiplexed_model_ids:
            actor._multiplexed_model_ids.remove(model_id)
    except Exception:
        pass


def get_multiplexed_model_id() -> str:
    from ray_tpu.serve._private.replica import get_multiplexed_model_id as g

    return g()
