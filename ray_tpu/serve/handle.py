"""DeploymentHandle: Python-native calls into a deployment.

Reference: python/ray/serve/handle.py — DeploymentHandle (:714) routes
through a Router; calls return DeploymentResponse (lazy future over an
ObjectRef) supporting .result() and await.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, Optional

import ray_tpu
from ray_tpu.serve._private.common import RequestMetadata


class DeploymentResponse:
    """Future-like result of handle.remote() (reference handle.py
    DeploymentResponse)."""

    def __init__(self, ref, fut, release_cb=None):
        self._ref = ref
        self._fut = fut
        self._release_cb = release_cb

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout_s)

    def cancel(self) -> None:
        """Abandon the request: release its scheduler slot immediately
        (a hung replica must not count as ongoing load forever) and
        best-effort cancel the task (reference: DeploymentResponse
        .cancel())."""
        cb, self._release_cb = self._release_cb, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass
        try:
            ray_tpu.cancel(self._ref)
        except Exception:
            pass

    def __await__(self):
        async def _get():
            values = await asyncio.wrap_future(self._fut)
            return values[0]

        return _get().__await__()

    @property
    def object_ref(self):
        """The underlying ObjectRef (pass to other tasks for zero-copy
        composition)."""
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment: str, app_name: str,
                 method_name: str = "__call__",
                 multiplexed_model_id: str = "",
                 stream: bool = False):
        self.deployment_name = deployment
        self.app_name = app_name
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = stream
        self._router = None

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name=method_name or self._method_name,
            multiplexed_model_id=(multiplexed_model_id
                                  if multiplexed_model_id is not None
                                  else self._multiplexed_model_id),
            stream=self._stream if stream is None else stream)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def _get_router(self):
        if self._router is None:
            from ray_tpu.serve._private.router import Router
            from ray_tpu.serve.api import _get_controller

            self._router = Router.shared(_get_controller(), self.app_name,
                                         self.deployment_name)
        return self._router

    def remote(self, *args, **kwargs):
        meta = RequestMetadata(
            request_id=uuid.uuid4().hex,
            call_method=self._method_name,
            multiplexed_model_id=self._multiplexed_model_id,
            stream=self._stream)
        ref, fut, replica, release = self._get_router().assign_request(
            meta, args, kwargs)
        if self._stream:
            return DeploymentResponseGenerator(ref, replica, release)
        return DeploymentResponse(ref, fut, release)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method_name,
                 self._multiplexed_model_id, self._stream))


class DeploymentResponseGenerator:
    """Iterates a streaming deployment response (reference:
    handle.options(stream=True) -> DeploymentResponseGenerator).

    Wraps the core ObjectRefGenerator of the replica's streaming actor
    call: chunks stream to this process as they're yielded — no
    per-chunk RPC round trip — and each __next__ resolves the next
    chunk's value."""

    def __init__(self, gen, replica_handle, release_cb=None):
        self._gen = gen            # core ObjectRefGenerator
        self._replica = replica_handle
        self._release_cb = release_cb
        self._done = False

    def _release(self) -> None:
        cb, self._release_cb = self._release_cb, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        try:
            ref = next(self._gen)
        except BaseException:
            # Stream end or mid-stream failure both terminate the
            # iterator and release the scheduler slot.
            self._done = True
            self._release()
            raise
        return ray_tpu.get(ref)

    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._done:
            raise StopAsyncIteration
        try:
            ref = await self._gen.__anext__()
        except BaseException:
            self._done = True
            self._release()
            raise
        # Large chunks live in the replica node's plasma: resolve off the
        # event loop so other in-flight requests aren't stalled.
        return await asyncio.get_running_loop().run_in_executor(
            None, ray_tpu.get, ref)

    def cancel(self) -> None:
        if self._done:
            return
        self._done = True
        try:
            self._gen.cancel()
        finally:
            self._release()

    def __del__(self):
        # An abandoned generator must not leak the replica-side stream
        # (it counts as an ongoing request until drained/cancelled).
        try:
            if not self._done:
                self.cancel()
        except Exception:
            pass
