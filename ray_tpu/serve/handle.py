"""DeploymentHandle: Python-native calls into a deployment.

Reference: python/ray/serve/handle.py — DeploymentHandle (:714) routes
through a Router; calls return DeploymentResponse (lazy future over an
ObjectRef) supporting .result() and await.
"""

from __future__ import annotations

import asyncio
import uuid
from typing import Any, Optional

import ray_tpu
from ray_tpu.serve._private.common import RequestMetadata


class DeploymentResponse:
    """Future-like result of handle.remote() (reference handle.py
    DeploymentResponse)."""

    def __init__(self, ref, fut):
        self._ref = ref
        self._fut = fut

    def result(self, timeout_s: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout_s)

    def __await__(self):
        async def _get():
            values = await asyncio.wrap_future(self._fut)
            return values[0]

        return _get().__await__()

    @property
    def object_ref(self):
        """The underlying ObjectRef (pass to other tasks for zero-copy
        composition)."""
        return self._ref


class DeploymentHandle:
    def __init__(self, deployment: str, app_name: str,
                 method_name: str = "__call__",
                 multiplexed_model_id: str = ""):
        self.deployment_name = deployment
        self.app_name = app_name
        self._method_name = method_name
        self._multiplexed_model_id = multiplexed_model_id
        self._router = None

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None
                ) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            method_name=method_name or self._method_name,
            multiplexed_model_id=(multiplexed_model_id
                                  if multiplexed_model_id is not None
                                  else self._multiplexed_model_id))

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def _get_router(self):
        if self._router is None:
            from ray_tpu.serve._private.router import Router
            from ray_tpu.serve.api import _get_controller

            self._router = Router.shared(_get_controller(), self.app_name,
                                         self.deployment_name)
        return self._router

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        meta = RequestMetadata(
            request_id=uuid.uuid4().hex,
            call_method=self._method_name,
            multiplexed_model_id=self._multiplexed_model_id)
        ref, fut = self._get_router().assign_request(meta, args, kwargs)
        return DeploymentResponse(ref, fut)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method_name,
                 self._multiplexed_model_id))
