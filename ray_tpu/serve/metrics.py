"""Serve metrics API: context-tagged Counter/Gauge/Histogram + the
autoscaling custom-metric hook.

Reference: python/ray/serve/metrics.py:69 (Counter/Gauge/Histogram that
auto-inject the serve replica context tags so user metrics are
per-deployment/replica without manual tagging) and :190 (histogram
variant). The replica's BUILT-IN request/error/latency metrics live in
_private/replica.py; this module is the user-facing seam.

``record_autoscaling_metric(value)`` publishes a per-replica scalar the
controller scales on when the deployment's AutoscalingConfig sets
``target_custom_metric`` (reference:
python/ray/serve/_private/autoscaling_policy.py's metric plumbing).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ray_tpu.util import metrics as _um

SERVE_TAG_KEYS = ("deployment", "replica", "application")


def _context_tags() -> Dict[str, str]:
    from ray_tpu.serve._private.replica import get_current_replica

    rep = get_current_replica()
    if rep is None:
        return {}
    return {"deployment": rep._deployment, "replica": rep._replica_id,
            "application": rep._app_name}


class _ServeTagged:
    """Mixin: serve context tags are appended to tag_keys and injected
    as defaults at construction (inside a replica) or lazily on first
    record (constructed at import time, before the replica exists)."""

    def _init_serve_tags(self):
        ctx = _context_tags()
        if ctx:
            merged = dict(self._default_tags)
            merged.update(ctx)
            self._default_tags = merged
            self._ctx_bound = True
        else:
            self._ctx_bound = False

    def _bind_ctx(self):
        if not self._ctx_bound:
            ctx = _context_tags()
            if ctx:
                merged = dict(self._default_tags)
                merged.update(ctx)
                self._default_tags = merged
                self._ctx_bound = True


class Counter(_ServeTagged, _um.Counter):
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description,
                         tuple(tag_keys or ()) + SERVE_TAG_KEYS)
        self._init_serve_tags()

    def inc(self, value: float = 1.0, tags=None) -> None:
        self._bind_ctx()
        super().inc(value, tags)


class Gauge(_ServeTagged, _um.Gauge):
    def __init__(self, name: str, description: str = "",
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description,
                         tuple(tag_keys or ()) + SERVE_TAG_KEYS)
        self._init_serve_tags()

    def set(self, value: float, tags=None) -> None:
        self._bind_ctx()
        super().set(value, tags)


class Histogram(_ServeTagged, _um.Histogram):
    def __init__(self, name: str, description: str = "",
                 boundaries=None,
                 tag_keys: Optional[Tuple[str, ...]] = None):
        super().__init__(name, description,
                         boundaries=boundaries,
                         tag_keys=tuple(tag_keys or ()) + SERVE_TAG_KEYS)
        self._init_serve_tags()

    def observe(self, value: float, tags=None) -> None:
        self._bind_ctx()
        super().observe(value, tags)


_engine_stat_gauges: Dict[str, Gauge] = {}


def report_engine_stats(stats: Dict[str, float],
                        prefix: str = "serve_llm_engine") -> None:
    """Publish a DecodeEngine ``stats()`` snapshot through the serve
    metric plane: every numeric field becomes a ``<prefix>_<field>``
    gauge carrying the replica's deployment/replica/application context
    tags, so engine health (queue depth, slot occupancy, TTFT/TPOT
    means, token counters) lands on the same GCS → dashboard /metrics
    Prometheus path as the built-in request series.

    Call it from the replica that owns the engine — typically once per
    stepper-loop iteration or on a timer:

        emitted = self.engine.step()
        serve.metrics.report_engine_stats(self.engine.stats())

    The engine's OWN util.metrics instruments (llm_engine_*) are
    engine-tagged but replica-blind; this is the deployment-tagged
    view. Gauges are cached per field, so per-step calls only pay a
    dict update. Outside a replica the gauges still record, just
    without context tags (same contract as user serve metrics).

    Every NUMERIC stats field passes through — including the
    tensor-parallel plane a sharded replica reports
    (``serve_llm_engine_tp_degree``,
    ``serve_llm_engine_host_transfer_bytes`` and its per-token ratio)
    — so a fleet of tp-sharded replicas needs no extra wiring to get
    per-replica mesh telemetry on the dashboard path."""
    for field, value in stats.items():
        if not isinstance(value, (int, float)):
            continue
        name = f"{prefix}_{field}"
        g = _engine_stat_gauges.get(name)
        if g is None:
            g = _engine_stat_gauges[name] = Gauge(
                name, f"DecodeEngine stats field {field!r}")
        g.set(float(value))


def record_autoscaling_metric(value: float) -> None:
    """Publish this replica's current value of the deployment's custom
    autoscaling metric. The controller averages the per-replica values
    it polls and scales toward ``target_custom_metric`` when the
    deployment's AutoscalingConfig declares one. Must be called inside
    a replica."""
    from ray_tpu.serve._private.replica import get_current_replica

    rep = get_current_replica()
    if rep is None:
        raise RuntimeError(
            "record_autoscaling_metric must be called inside a serve "
            "replica")
    rep._custom_autoscaling_metric = float(value)


def recorded_autoscaling_metric() -> Optional[float]:
    """Read back the scalar this replica last published via
    ``record_autoscaling_metric`` — None outside a replica or before
    the first record.

    This is the consumer half of the custom-metric seam: the LLM fleet
    autoscaler (models/fleet.py) takes it as its default
    ``custom_metric_source`` when a deployment declares
    ``target_custom_metric``, so a scalar the replica records (tokens
    in flight, app-level queue length, anything) directly drives
    scale decisions — the same loop the reference controller runs by
    polling ``get_autoscaling_metric`` off each replica."""
    from ray_tpu.serve._private.replica import get_current_replica

    rep = get_current_replica()
    if rep is None:
        return None
    return rep.get_autoscaling_metric()
