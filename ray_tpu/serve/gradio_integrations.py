"""Gradio integration, gated on the ``gradio`` package.

Reference: python/ray/serve/gradio_integrations.py:18 (GradioServer —
wrap a Gradio Blocks app as a Serve deployment so it scales/replicates
like any deployment; GradioIngress for composing with handles).
"""

from __future__ import annotations

from typing import Any, Callable

from ray_tpu import serve


def _import_gradio():
    try:
        import gradio
    except ImportError as e:
        raise ImportError(
            "gradio is not installed (`pip install gradio`); "
            "GradioServer wraps a gradio Blocks app as a Serve "
            "deployment") from e
    return gradio


class GradioIngress:
    """Base for deployments that front a Gradio app: the builder
    returns a ``gradio.Blocks``; requests route into its ASGI app."""

    def __init__(self, builder: Callable[[], Any]):
        gradio = _import_gradio()
        self._blocks = builder()
        if not isinstance(self._blocks, gradio.Blocks):
            raise TypeError(
                f"builder must return gradio.Blocks, got "
                f"{type(self._blocks).__name__}")
        self._app = gradio.routes.App.create_app(self._blocks)

    async def __call__(self, request):
        """Delegate the HTTP request into gradio's ASGI app through the
        serve ASGI bridge."""
        from ray_tpu.serve.asgi import run_asgi

        return await run_asgi(self._app, request)


def GradioServer(builder: Callable[[], Any]):
    """A ready-to-bind Serve deployment hosting the Gradio app
    (reference: GradioServer). Usage:

        app = GradioServer(lambda: build_my_blocks()).bind()
        serve.run(app)
    """
    _import_gradio()  # fail at build time, not replica start

    @serve.deployment(name="GradioServer")
    class _GradioServer(GradioIngress):
        def __init__(self):
            super().__init__(builder)

    return _GradioServer
