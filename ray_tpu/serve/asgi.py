"""ASGI ingress — run any ASGI application inside a deployment.

Reference: python/ray/serve/api.py `@serve.ingress(app)` +
_private/http_util.py (the ASGI adapter that replays the proxied request
into the app and captures its response). Framework-agnostic: anything
implementing the ASGI 3.0 callable protocol works — FastAPI/Starlette
when installed, or hand-written apps in hermetic images.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional
from urllib.parse import urlencode

from ray_tpu.serve._private.proxy import ServeRequest


class HTTPResponse:
    """Structured HTTP response a deployment may return (the proxy maps
    it to status/headers/body; plain bytes/str/json returns still work)."""

    def __init__(self, body: bytes = b"", status: int = 200,
                 headers: Optional[Dict[str, str]] = None):
        self.body = body
        self.status = status
        self.headers = dict(headers or {})

    def __reduce__(self):
        return (HTTPResponse, (self.body, self.status, self.headers))


def _scope_of(request: ServeRequest) -> Dict[str, Any]:
    return {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.method,
        "scheme": "http",
        "path": request.path,
        "raw_path": request.path.encode(),
        "root_path": (request.route_prefix
                      if request.route_prefix != "/" else ""),
        "query_string": urlencode(request.query_params).encode(),
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in request.headers.items()],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 0),
    }


async def run_asgi(app: Callable, request: ServeRequest) -> HTTPResponse:
    """Replay the proxied request into `app`, capture its response."""
    body_sent = [False]

    async def receive():
        if body_sent[0]:
            return {"type": "http.disconnect"}
        body_sent[0] = True
        return {"type": "http.request", "body": request.body or b"",
                "more_body": False}

    status = [500]
    headers: List = []
    chunks: List[bytes] = []

    async def send(message):
        if message["type"] == "http.response.start":
            status[0] = message["status"]
            headers.extend(message.get("headers", []))
        elif message["type"] == "http.response.body":
            chunks.append(bytes(message.get("body", b"")))

    await app(_scope_of(request), receive, send)
    return HTTPResponse(
        body=b"".join(chunks),
        status=status[0],
        headers={k.decode(): v.decode() for k, v in headers})


def ingress(app: Any):
    """Class decorator: route the deployment's HTTP traffic through an
    ASGI app (reference: serve.ingress).

    Use below @serve.deployment::

        app = MyAsgiApp()          # any ASGI-3 callable

        @serve.deployment
        @serve.ingress(app)
        class Frontend:
            ...

    The app sees the standard ASGI scope (root_path = the deployment's
    route prefix). Decorating a class directly (``@serve.ingress`` with
    no app) stays an identity marker for backward compatibility.
    """
    if isinstance(app, type):  # legacy identity-marker usage
        return app

    def decorator(cls: type) -> type:
        class ASGIIngress(cls):
            async def __call__(self, request: ServeRequest):
                return await run_asgi(app, request)

        ASGIIngress.__name__ = cls.__name__
        ASGIIngress.__qualname__ = getattr(cls, "__qualname__",
                                           cls.__name__)
        ASGIIngress.__module__ = cls.__module__
        ASGIIngress.__serve_asgi_app__ = app
        return ASGIIngress

    return decorator
