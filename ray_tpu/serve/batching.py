"""@serve.batch — transparent request batching.

Reference: python/ray/serve/batching.py — queued calls are flushed to the
underlying method as a list once max_batch_size accumulate or
batch_wait_timeout_s elapses; each caller gets its element back.
"""

from __future__ import annotations

import asyncio
import functools
import inspect
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max_batch_size = max_batch_size
        self._timeout_s = batch_wait_timeout_s
        self._queue: List[tuple] = []  # (item, future)
        self._flush_task: Optional[asyncio.Task] = None

    async def submit(self, item: Any) -> Any:
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._queue.append((item, fut))
        if len(self._queue) >= self._max_batch_size:
            await self._flush()
        elif self._flush_task is None or self._flush_task.done():
            self._flush_task = loop.create_task(self._delayed_flush())
        return await fut

    async def _delayed_flush(self) -> None:
        await asyncio.sleep(self._timeout_s)
        await self._flush()

    async def _flush(self) -> None:
        if not self._queue:
            return
        batch, self._queue = self._queue, []
        items = [b[0] for b in batch]
        futs = [b[1] for b in batch]
        try:
            if inspect.iscoroutinefunction(self._fn):
                results = await self._fn(items)
            else:
                results = await asyncio.get_running_loop().run_in_executor(
                    None, self._fn, items)
            if len(results) != len(items):
                raise ValueError(
                    f"batched function returned {len(results)} results for "
                    f"{len(items)} inputs")
            for fut, r in zip(futs, results):
                if not fut.done():
                    fut.set_result(r)
        except Exception as e:
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorate a method taking a list of inputs; callers pass single
    inputs and get single outputs (reference serve.batch)."""

    def wrap(fn):
        queues = {}  # per bound instance (or None for free functions)

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:  # bound method: (self, item)
                owner, item = args
                bound = functools.partial(fn, owner)
                key = id(owner)
            elif len(args) == 1:
                owner, item = None, args[0]
                bound = fn
                key = None
            else:
                raise TypeError(
                    "@serve.batch methods take exactly one request argument")
            q = queues.get(key)
            if q is None:
                q = queues[key] = _BatchQueue(bound, max_batch_size,
                                              batch_wait_timeout_s)
            return await q.submit(item)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
