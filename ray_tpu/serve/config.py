"""Serve config dataclasses.

Reference: python/ray/serve/config.py (AutoscalingConfig, HTTPOptions),
python/ray/serve/_private/config.py (DeploymentConfig, ReplicaConfig).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Request-driven autoscaling (reference: python/ray/serve/config.py
    AutoscalingConfig; policy python/ray/serve/autoscaling_policy.py)."""

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    initial_replicas: Optional[int] = None
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 60.0
    upscaling_factor: Optional[float] = None
    downscaling_factor: Optional[float] = None
    metrics_interval_s: float = 1.0
    look_back_period_s: float = 10.0
    # When set, scale on the replicas' user-recorded custom metric
    # (serve.metrics.record_autoscaling_metric) instead of ongoing
    # requests: desired = ceil(sum(custom) / target_custom_metric).
    target_custom_metric: Optional[float] = None

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.__dataclass_fields__}

    @staticmethod
    def from_dict(d: Optional[dict]) -> Optional["AutoscalingConfig"]:
        if d is None:
            return None
        return AutoscalingConfig(**d)


@dataclass
class DeploymentConfig:
    """Per-deployment behavior knobs (reference:
    python/ray/serve/_private/config.py DeploymentConfig)."""

    num_replicas: int = 1
    max_ongoing_requests: int = 5
    autoscaling_config: Optional[AutoscalingConfig] = None
    user_config: Any = None
    graceful_shutdown_timeout_s: float = 20.0
    graceful_shutdown_wait_loop_s: float = 2.0
    health_check_period_s: float = 10.0
    health_check_timeout_s: float = 30.0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    version: Optional[str] = None

    def to_dict(self) -> dict:
        d = {k: getattr(self, k) for k in self.__dataclass_fields__}
        if self.autoscaling_config is not None:
            d["autoscaling_config"] = self.autoscaling_config.to_dict()
        return d

    @staticmethod
    def from_dict(d: dict) -> "DeploymentConfig":
        d = dict(d)
        d["autoscaling_config"] = AutoscalingConfig.from_dict(
            d.get("autoscaling_config"))
        return DeploymentConfig(**d)


@dataclass
class HTTPOptions:
    """Proxy bind options (reference: python/ray/serve/config.py
    HTTPOptions)."""

    host: str = "127.0.0.1"
    port: int = 8000
    # End-to-end request bound; on expiry the client gets 504 and the
    # replica slot is released (None = wait forever).
    request_timeout_s: Optional[float] = 60.0
    # Optional TLS for the gRPC ingress:
    # {"cert_path", "key_path", "ca_path"(opt -> mTLS)}.
    grpc_tls: Optional[dict] = None

    def to_dict(self) -> dict:
        return {"host": self.host, "port": self.port,
                "request_timeout_s": self.request_timeout_s,
                "grpc_tls": self.grpc_tls}
