"""Runtime environments: per-task/actor execution environments.

Reference: python/ray/_private/runtime_env/ (plugins: env_vars,
working_dir, py_modules, pip/conda; per-node agent with URI caching,
uri_cache.py; packaging = zips in the GCS KV). Simplification, same
contract: the driver packages local dirs into content-addressed zips in
the GCS KV; workers materialize them once per node into a shared cache
and apply the env (env vars, sys.path, cwd) around user-code execution.

pip/conda are hermetic-aware: pip installs from an allowlisted LOCAL
index into content-addressed cached dirs (live installs gated on
RAY_TPU_ALLOW_PIP=1); conda accepts NAMED pre-built envs, which swap the
dedicated actor worker's interpreter at the raylet spawn path
(RAY_TPU_CONDA_ROOT/envs/<name> or a prefix path) — spec-form conda
(dependency solving) stays gated.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import zipfile
from typing import Any, Dict, List, Optional

_KV_NS = b"runtime_env_pkg"
_CACHE_ROOT = os.path.join(
    os.environ.get("RAY_TPU_TMPDIR", "/tmp/ray_tpu"), "runtime_envs")
_cache_lock = threading.Lock()
_materialized: Dict[str, str] = {}  # uri -> extracted dir

EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 100 * 1024 * 1024


# Driver-side upload memo: abspath -> (dir signature, uri). The signature
# (file count + newest mtime + total size) is a cheap walk; only a changed
# dir re-zips and re-uploads (reference: upload cache in packaging.py).
_upload_cache: Dict[str, tuple] = {}


def _dir_signature(path: str) -> tuple:
    count = 0
    newest = 0.0
    total = 0
    for root, dirs, files in os.walk(path):
        dirs[:] = [d for d in dirs if d not in EXCLUDE_DIRS]
        for name in files:
            try:
                st = os.stat(os.path.join(root, name))
            except OSError:
                continue
            count += 1
            total += st.st_size
            newest = max(newest, st.st_mtime)
    return (count, total, newest)


def package_local_dir(path: str, gcs_call) -> str:
    """Zip `path` and store it in the GCS KV under a content hash.
    Returns the package URI (reference: packaging.py upload_package)."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env dir {path!r} does not exist")
    sig = _dir_signature(path)
    with _cache_lock:
        cached = _upload_cache.get(path)
        if cached and cached[0] == sig:
            return cached[1]
    buf = tempfile.SpooledTemporaryFile(max_size=MAX_PACKAGE_BYTES)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in EXCLUDE_DIRS]
            for name in files:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                zf.write(full, rel)
    buf.seek(0)
    blob = buf.read()
    if len(blob) > MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path!r} exceeds "
            f"{MAX_PACKAGE_BYTES >> 20} MiB")
    digest = hashlib.sha1(blob).hexdigest()
    uri = f"gcs://{digest}"
    gcs_call("kv_put", {"ns": _KV_NS, "key": digest.encode(),
                        "value": blob, "overwrite": False})
    with _cache_lock:
        _upload_cache[path] = (sig, uri)
    return uri


def merge_runtime_envs(base: Optional[dict],
                       override: Optional[dict]) -> Optional[dict]:
    """Job-level env under a per-call env: per-call keys win, env_vars
    union (per-call entries shadow job entries)."""
    if not base:
        return override
    merged = dict(base)
    if override:
        env_vars = {**merged.get("env_vars", {}),
                    **override.get("env_vars", {})}
        merged.update(override)
        if env_vars:
            merged["env_vars"] = env_vars
    return merged


def prepare_runtime_env(runtime_env: Optional[dict],
                        gcs_call) -> Optional[dict]:
    """Driver-side: replace local paths with uploaded package URIs.
    Called at task/actor submission (reference: runtime_env validation +
    upload in remote_function/actor options plumbing)."""
    if not runtime_env:
        return runtime_env
    env = dict(runtime_env)
    wd = env.get("working_dir")
    if wd and not str(wd).startswith("gcs://"):
        env["working_dir"] = package_local_dir(wd, gcs_call)
    mods = env.get("py_modules")
    if mods:
        env["py_modules"] = [
            m if str(m).startswith("gcs://")
            else package_local_dir(m, gcs_call)
            for m in mods]
    return env


def _materialize(uri: str, gcs_call) -> str:
    """Download+extract a package URI once per node (uri_cache.py)."""
    with _cache_lock:
        cached = _materialized.get(uri)
        if cached and os.path.isdir(cached):
            return cached
    digest = uri[len("gcs://"):]
    dest = os.path.join(_CACHE_ROOT, digest)
    done_marker = os.path.join(dest, ".ray_tpu_ready")
    if not os.path.exists(done_marker):
        blob = gcs_call("kv_get", {"ns": _KV_NS, "key": digest.encode()})
        if blob is None:
            raise RuntimeError(f"runtime_env package {uri} not in GCS")
        tmp = dest + f".tmp.{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        import io

        with zipfile.ZipFile(io.BytesIO(blob)) as zf:
            zf.extractall(tmp)
        open(os.path.join(tmp, ".ray_tpu_ready"), "w").close()
        try:
            os.replace(tmp, dest)
        except OSError:
            # Another worker won the race; use theirs.
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.exists(done_marker):
                raise
    with _cache_lock:
        _materialized[uri] = dest
    return dest


_pip_site_dirs: Dict[tuple, str] = {}  # env key -> installed site dir


def _pip_spec(env: dict) -> Optional[tuple]:
    """Normalize runtime_env['pip'] to (packages tuple, index path)."""
    reqs = env.get("pip")
    if not reqs:
        return None
    index = os.environ.get("RAY_TPU_PIP_INDEX", "")
    if isinstance(reqs, dict):
        index = reqs.get("index", index)
        reqs = reqs.get("packages", [])
    return tuple(sorted(map(str, reqs))), index


def _check_pip(env: dict) -> Optional[str]:
    """pip plugin (reference: _private/runtime_env/pip.py): builds a
    content-addressed cached package dir per requirements set and returns
    it for sys.path application. Installation is gated on an allowlisted
    LOCAL index (RAY_TPU_PIP_INDEX or pip.index — `--no-index
    --find-links` semantics; no network), unless RAY_TPU_ALLOW_PIP=1
    explicitly opts into a live index install.

    The cache key is sha1(packages + index): a second job with the same
    requirements reuses the installed dir without invoking pip."""
    spec = _pip_spec(env)
    if spec is None:
        return None
    reqs, index = spec
    allow_live = os.environ.get("RAY_TPU_ALLOW_PIP") == "1"
    if not index and not allow_live:
        raise RuntimeError(
            "runtime_env['pip'] requested but this deployment is hermetic "
            "(no package index). Provide a local index via "
            "RAY_TPU_PIP_INDEX / pip['index'], or set RAY_TPU_ALLOW_PIP=1 "
            "to attempt a live `pip install`.")
    with _cache_lock:
        cached = _pip_site_dirs.get(spec)
        if cached and os.path.isdir(cached):
            return cached
    digest = hashlib.sha1(
        repr((reqs, index)).encode()).hexdigest()[:16]
    dest = os.path.join(_CACHE_ROOT, "pip", digest)
    marker = os.path.join(dest, ".ray_tpu_ready")
    if not os.path.exists(marker):
        tmp = dest + f".tmp.{os.getpid()}"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        cmd = [sys.executable, "-m", "pip", "install",
               "--quiet", "--no-warn-script-location",
               "--target", tmp, *reqs]
        if index:
            cmd += ["--no-index", "--find-links", index]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeError(
                f"pip install of {list(reqs)} failed:\n"
                f"{proc.stderr[-2000:]}")
        open(os.path.join(tmp, ".ray_tpu_ready"), "w").close()
        try:
            os.replace(tmp, dest)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            if not os.path.exists(marker):
                raise
    with _cache_lock:
        _pip_site_dirs[spec] = dest
    return dest


def _check_conda(runtime_env: dict, actor_worker: bool) -> None:
    """conda plugin (reference: _private/runtime_env/conda.py): a NAMED
    pre-built env swaps the worker interpreter — enforced at the raylet
    spawn path (`Raylet._resolve_conda_python`), which is the only place
    an interpreter swap can happen. On an actor worker a conda name is a
    no-op here: this process IS the env's interpreter (dedicated lease).
    Plain tasks run on shared pool workers (no interpreter swap
    possible) and must reject it. Spec-form conda (dependency lists)
    needs a solver the hermetic deployment doesn't have."""
    conda = runtime_env.get("conda")
    if not conda:
        return
    if isinstance(conda, dict):
        raise RuntimeError(
            "runtime_env conda specs (dependency lists) are not supported "
            "in this hermetic deployment; pre-build the env and pass its "
            "name (under RAY_TPU_CONDA_ROOT) or prefix path")
    if not actor_worker:
        raise RuntimeError(
            "runtime_env['conda'] applies to ACTORS in this deployment "
            "(dedicated worker processes get the env's interpreter); "
            "plain tasks run on shared pool workers — wrap the work in "
            "an actor or use py_modules/pip instead")


@contextlib.contextmanager
def applied_runtime_env(runtime_env: Optional[dict], gcs_call):
    """Worker-side: apply env vars / working_dir / py_modules around user
    code, restoring afterwards (workers are shared across envs here,
    unlike the reference's dedicated-worker model — restore is required).
    """
    if not runtime_env:
        yield
        return
    _check_conda(runtime_env, actor_worker=False)
    pip_dir = _check_pip(runtime_env)

    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd = os.getcwd()
    added_paths: List[str] = []
    try:
        if pip_dir:
            sys.path.insert(0, pip_dir)
            added_paths.append(pip_dir)
        for key, value in (runtime_env.get("env_vars") or {}).items():
            saved_env[key] = os.environ.get(key)
            os.environ[key] = str(value)
        wd_uri = runtime_env.get("working_dir")
        if wd_uri:
            wd = _materialize(wd_uri, gcs_call)
            os.chdir(wd)
            sys.path.insert(0, wd)
            added_paths.append(wd)
        for uri in runtime_env.get("py_modules") or []:
            mod_dir = _materialize(uri, gcs_call)
            sys.path.insert(0, mod_dir)
            added_paths.append(mod_dir)
        yield
    finally:
        for p in added_paths:
            with contextlib.suppress(ValueError):
                sys.path.remove(p)
        # Evict modules imported FROM the env's paths: workers are shared
        # across envs here (unlike the reference's dedicated workers), so
        # sys.modules residue would leak the env's packages into later
        # tasks (and pin stale code across env versions).
        if added_paths:
            prefixes = tuple(p.rstrip(os.sep) + os.sep for p in added_paths)
            for name, mod in list(sys.modules.items()):
                f = getattr(mod, "__file__", None)
                if f and f.startswith(prefixes):
                    del sys.modules[name]
        with contextlib.suppress(OSError):
            os.chdir(saved_cwd)
        for key, old in saved_env.items():
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old


def apply_runtime_env_permanent(runtime_env: Optional[dict],
                                gcs_call) -> None:
    """Apply without restore — for actor workers, which are DEDICATED to
    their actor for the process lifetime (matching the reference's
    dedicated-worker-per-env model). Permanent application makes the env
    visible to sync AND async methods and is safe under
    max_concurrency>1 (no save/restore races)."""
    if not runtime_env:
        return
    # Only actor workers apply envs permanently (dedicated processes).
    _check_conda(runtime_env, actor_worker=True)
    pip_dir = _check_pip(runtime_env)
    if pip_dir:
        sys.path.insert(0, pip_dir)
    for key, value in (runtime_env.get("env_vars") or {}).items():
        os.environ[key] = str(value)
    wd_uri = runtime_env.get("working_dir")
    if wd_uri:
        wd = _materialize(wd_uri, gcs_call)
        os.chdir(wd)
        sys.path.insert(0, wd)
    for uri in runtime_env.get("py_modules") or []:
        sys.path.insert(0, _materialize(uri, gcs_call))
