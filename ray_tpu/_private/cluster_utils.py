"""Multi-node-on-one-machine test clusters.

Equivalent of the reference's cluster_utils.Cluster
(python/ray/cluster_utils.py:135): starts one GCS plus N real raylet
processes on this machine, each with its own shm store and resource spec
(e.g. fake ``{"TPU": 4}`` + slice ids), so distributed scheduling,
spillback, gang placement, and failover are exercised with the real control
plane — only the hardware is simulated.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

from ray_tpu.core.config import Config
from ray_tpu._private.node import Node


class Cluster:
    def __init__(self, config: Optional[Config] = None,
                 _existing_address: Optional[str] = None):
        """_existing_address: join an already-running GCS instead of
        starting a head on the first add_node (autoscaler providers add
        nodes to a live cluster)."""
        self.config = config or Config.from_env()
        self.head: Optional[Node] = None
        self.nodes: list[Node] = []
        self._existing_address = _existing_address
        self.session_dir = os.path.join(
            self.config.temp_dir,
            f"cluster_{int(time.time() * 1000)}_{os.getpid()}")

    @property
    def address(self) -> str:
        return self._existing_address or self.head.gcs_address

    def add_node(self, resources: Optional[Dict[str, float]] = None,
                 slice_id: str = "",
                 labels: Optional[Dict[str, str]] = None) -> Node:
        """Add a raylet process (the first call also starts the GCS,
        unless the cluster joins an existing address)."""
        gcs_address = self._existing_address or (
            self.head.gcs_address if self.head else None)
        node = Node(
            self.config,
            resources=resources or {"CPU": 2.0},
            gcs_address=gcs_address,
            session_dir=self.session_dir,
            labels=labels,
            slice_id=slice_id,
        )
        node.start()
        if self.head is None and self._existing_address is None:
            self.head = node
        self.nodes.append(node)
        return node

    def remove_node(self, node: Node) -> None:
        """Kill a node's raylet (simulates node failure)."""
        node.kill_raylet()
        if node in self.nodes:
            self.nodes.remove(node)

    def wait_for_nodes(self, n: int, timeout: float = 30.0) -> None:
        import asyncio

        from ray_tpu.core import rpc

        async def poll():
            host, port = self.address.rsplit(":", 1)
            conn = await rpc.connect(host, int(port))
            deadline = time.monotonic() + timeout
            try:
                while time.monotonic() < deadline:
                    nodes = await conn.call("get_nodes")
                    if sum(1 for x in nodes if x["state"] == "ALIVE") >= n:
                        return True
                    await asyncio.sleep(0.1)
                return False
            finally:
                await conn.close()

        if not asyncio.run(poll()):
            raise TimeoutError(f"cluster did not reach {n} alive nodes")

    def wait_for_view_converged(self, timeout: float = 15.0) -> None:
        """Block until every raylet's cluster resource view matches the
        GCS node table (all nodes visible, availability in agreement).
        Deterministic replacement for sleep/retry in spillback tests:
        scheduling decisions made after this see a converged view."""
        import asyncio

        from ray_tpu.core import rpc

        async def poll():
            ghost, gport = self.address.rsplit(":", 1)
            gconn = await rpc.connect(ghost, int(gport))
            rconns: dict = {}  # address -> conn, reused across poll rounds
            deadline = time.monotonic() + timeout
            try:
                while time.monotonic() < deadline:
                    nodes = await gconn.call("get_nodes")
                    alive = {n["node_id"]: n for n in nodes
                             if n["state"] == "ALIVE"}
                    ok = True
                    for n in alive.values():
                        try:
                            rconn = rconns.get(n["address"])
                            if rconn is None or rconn.closed:
                                host, port = n["address"].rsplit(":", 1)
                                rconn = rconns[n["address"]] = \
                                    await rpc.connect(host, int(port),
                                                      timeout=2.0)
                            view = await rconn.call("get_cluster_view")
                        except Exception:
                            ok = False
                            break
                        seen = {v["node_id"]: v for v in view}
                        for nid, expect in alive.items():
                            got = seen.get(nid)
                            if got is None or got["resources_available"] \
                                    != expect["resources_available"]:
                                ok = False
                                break
                        if not ok:
                            break
                    if ok:
                        return True
                    await asyncio.sleep(0.05)
                return False
            finally:
                for rconn in rconns.values():
                    try:
                        await rconn.close()
                    except Exception:
                        pass
                await gconn.close()

        if not asyncio.run(poll()):
            raise TimeoutError("raylet resource views did not converge")

    def shutdown(self) -> None:
        for node in self.nodes:
            node.shutdown()
        if self.head and self.head not in self.nodes:
            self.head.shutdown()
        self.nodes.clear()
        self.head = None
