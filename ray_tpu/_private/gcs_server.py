"""GCS — Global Control Service: the cluster control plane.

Equivalent of the reference's gcs_server (src/ray/gcs/gcs_server/
gcs_server.h:78) hosting, in one process: node manager + health checks
(gcs_health_check_manager.h:39), actor manager + scheduler
(gcs_actor_manager.cc:311, gcs_actor_scheduler.cc:49), placement-group
manager with 2-phase bundle commit (gcs_placement_group_manager.cc), job
manager, internal KV (function table rides on it), object directory,
task-event store (gcs_task_manager.h), and long-poll pubsub fan-out
(src/ray/pubsub/publisher.h:296 — here: push notifications over the
persistent RPC connections).

TPU-native addition: nodes register slice topology (slice_id, hosts per
slice, chips per host) and the placement-group SLICE strategy gang-schedules
one bundle per host of a single slice, atomically.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.core import rpc
from ray_tpu.core.config import Config
from ray_tpu.core.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu.core.task_spec import TaskSpec

logger = logging.getLogger(__name__)

# Actor states (reference: rpc::ActorTableData state machine)
PENDING, ALIVE, RESTARTING, DEAD = "PENDING", "ALIVE", "RESTARTING", "DEAD"


class NodeInfo:
    def __init__(self, node_id: NodeID, data: dict):
        self.node_id = node_id
        self.address: str = data["address"]
        self.hostname: str = data.get("hostname", "")
        self.store_path: str = data.get("store_path", "")
        self.resources_total: Dict[str, float] = dict(data["resources"])
        self.resources_available: Dict[str, float] = dict(data["resources"])
        self.labels: Dict[str, str] = data.get("labels", {})
        self.slice_id: str = data.get("slice_id", "")
        self.transfer_port: int = data.get("transfer_port", 0)
        self.state = ALIVE
        self.last_heartbeat = time.monotonic()
        self.conn: Optional[rpc.Connection] = None
        # Queued lease demands from the latest heartbeat (autoscaler input).
        self.pending_demands: List[Dict[str, float]] = []

    def view(self) -> dict:
        return {
            "node_id": self.node_id.binary(),
            "address": self.address,
            "hostname": self.hostname,
            "store_path": self.store_path,
            "resources_total": self.resources_total,
            "resources_available": self.resources_available,
            "labels": self.labels,
            "slice_id": self.slice_id,
            "transfer_port": self.transfer_port,
            "state": self.state,
        }

    @classmethod
    def from_store(cls, node_id: NodeID, v: dict) -> "NodeInfo":
        info = cls(node_id, {
            "address": v["address"], "hostname": v["hostname"],
            "store_path": v["store_path"],
            "resources": v["resources_total"], "labels": v["labels"],
            "slice_id": v["slice_id"],
            "transfer_port": v["transfer_port"]})
        info.resources_available = dict(v["resources_available"])
        info.state = v["state"]
        return info


class ActorInfo:
    def __init__(self, actor_id: ActorID, data: dict):
        self.actor_id = actor_id
        self.name: str = data.get("name") or ""
        self.namespace: str = data.get("namespace") or "default"
        self.class_name: str = data.get("class_name", "")
        self.max_restarts: int = data.get("max_restarts", 0)
        self.max_concurrency: int = data.get("max_concurrency", 1)
        self.detached: bool = data.get("detached", False)
        self.creation_task: dict = data["creation_task"]  # wire TaskSpec
        self.job_id: JobID = JobID(data["job_id"])
        self.state = PENDING
        self.address: str = ""
        self.fast_address: str = ""  # fastlane (native task path) port
        self.node_id: Optional[NodeID] = None
        self.num_restarts = 0
        self.death_cause: str = ""

    def view(self) -> dict:
        return {
            "actor_id": self.actor_id.binary(),
            "name": self.name,
            "namespace": self.namespace,
            "class_name": self.class_name,
            "state": self.state,
            "address": self.address,
            "fast_address": self.fast_address,
            "node_id": self.node_id.binary() if self.node_id else None,
            "job_id": self.job_id.binary(),
            "num_restarts": self.num_restarts,
            "max_restarts": self.max_restarts,
            "max_concurrency": self.max_concurrency,
            "death_cause": self.death_cause,
        }

    def to_store(self) -> dict:
        v = self.view()
        v["creation_task"] = self.creation_task
        v["detached"] = self.detached
        return v

    @classmethod
    def from_store(cls, actor_id: ActorID, v: dict) -> "ActorInfo":
        info = cls(actor_id, {
            "name": v["name"], "namespace": v["namespace"],
            "class_name": v["class_name"],
            "max_restarts": v["max_restarts"], "detached": v["detached"],
            "max_concurrency": v.get("max_concurrency", 1),
            "creation_task": v["creation_task"], "job_id": v["job_id"]})
        info.state = v["state"]
        info.address = v["address"]
        info.fast_address = v.get("fast_address", "")
        info.node_id = NodeID(v["node_id"]) if v.get("node_id") else None
        info.num_restarts = v["num_restarts"]
        info.death_cause = v["death_cause"]
        return info


class PlacementGroupInfo:
    def __init__(self, pg_id: PlacementGroupID, data: dict):
        self.pg_id = pg_id
        self.name: str = data.get("name", "")
        self.strategy: str = data.get("strategy", "PACK")
        self.bundles: List[Dict[str, float]] = data["bundles"]
        self.job_id = JobID(data["job_id"]) if data.get("job_id") else None
        self.state = "PENDING"
        # bundle index -> node_id
        self.bundle_locations: Dict[int, NodeID] = {}
        self.ready_event = asyncio.Event()

    def view(self) -> dict:
        return {
            "pg_id": self.pg_id.binary(),
            "name": self.name,
            "strategy": self.strategy,
            "bundles": self.bundles,
            "state": self.state,
            "bundle_locations": {
                str(i): n.binary() for i, n in self.bundle_locations.items()
            },
        }

    def to_store(self) -> dict:
        v = self.view()
        v["job_id"] = self.job_id.binary() if self.job_id else None
        return v

    @classmethod
    def from_store(cls, pg_id: PlacementGroupID,
                   v: dict) -> "PlacementGroupInfo":
        pg = cls(pg_id, {"name": v["name"], "strategy": v["strategy"],
                         "bundles": v["bundles"], "job_id": v["job_id"]})
        pg.state = v["state"]
        pg.bundle_locations = {
            int(i): NodeID(n) for i, n in v["bundle_locations"].items()}
        if pg.state == "CREATED":
            pg.ready_event.set()
        return pg


class GcsServer:
    def __init__(self, config: Config, persist_path: Optional[str] = None):
        from ray_tpu._private.gcs_storage import GcsTableStorage

        self.config = config
        # Write-through table persistence (reference: GcsTableStorage over
        # store_client/ — Redis there, sqlite here). persist_path=None
        # keeps the same code path on a volatile in-memory db.
        self.storage = GcsTableStorage(persist_path)
        self.kv: Dict[Tuple[bytes, bytes], bytes] = {}
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        # kill() for ids the GCS hasn't seen yet (cross-process kill
        # racing a pipelined registration) — see handle_kill_actor.
        # Insertion-ordered dict: pruning evicts oldest-first.
        self._kill_tombstones: Dict[ActorID, bool] = {}
        # wait_actor_alive wakeups: one Event per actor id with waiters,
        # fired (and dropped) on every state-affecting transition so
        # waiters re-check instead of polling on a 20 ms timer.
        self._actor_waiters: Dict[ActorID, asyncio.Event] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.jobs: Dict[JobID, dict] = {}
        self.object_locations: Dict[bytes, Set[bytes]] = {}
        self.spilled_objects: Dict[bytes, str] = {}
        self.task_events: List[dict] = []
        # Structured export events (reference: event.proto + the
        # dashboard event module): bounded newest-last ring.
        self.events: List[dict] = []
        # worker_id -> {"metrics": [...], "time": t}
        self.worker_metrics: Dict[bytes, dict] = {}
        # Counters/histograms folded in from dead workers — counter
        # totals must stay monotonic across worker churn.
        self.retired_metrics: Dict[tuple, dict] = {}
        self.retired_worker_ids: Set[bytes] = set()
        self.subscribers: Dict[str, Set[rpc.Connection]] = {}
        self._next_job = 0
        self._server: Optional[rpc.Server] = None
        self._bg: List[asyncio.Task] = []
        self._pg_lock = asyncio.Lock()

    # ------------------------------------------------------------- lifecycle
    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._restore_tables()
        self._server = rpc.Server(self, host, port)
        port = await self._server.start()
        self._bg.append(asyncio.get_running_loop().create_task(
            self._health_check_loop()))
        self._bg.append(asyncio.get_running_loop().create_task(
            self._broadcast_view_loop()))
        self.resume_restored_state()
        logger.info("GCS listening on %s:%s", host, port)
        return port

    # ----------------------------------------------------------- persistence
    def _restore_tables(self) -> None:
        """Rebuild in-memory state from durable tables after a head
        restart (reference: GCS recovery from Redis +
        HandleNotifyGCSRestart — raylets re-register, actors resume)."""
        for key, v in self.storage.load_all("kv"):
            ns, _, k = key.partition(b"\x00")
            self.kv[(ns, k)] = v
        for key, v in self.storage.load_all("nodes"):
            info = NodeInfo.from_store(NodeID(key), v)
            # Raylets re-register over fresh connections; give them a
            # grace period before health checks may fail them.
            info.last_heartbeat = time.monotonic() + 5.0
            self.nodes[info.node_id] = info
        for key, v in self.storage.load_all("jobs"):
            self.jobs[JobID(key)] = v
        for key, v in self.storage.load_all("actors"):
            info = ActorInfo.from_store(ActorID(key), v)
            self.actors[info.actor_id] = info
            if info.name and info.state != DEAD:
                self.named_actors[(info.namespace, info.name)] = \
                    info.actor_id
        for key, v in self.storage.load_all("pgs"):
            pg = PlacementGroupInfo.from_store(PlacementGroupID(key), v)
            self.placement_groups[pg.pg_id] = pg
        nj = self.storage.get("meta", b"next_job")
        if nj is not None:
            self._next_job = nj
        rr = self.storage.get("meta", b"requested_resources")
        if rr:
            self._requested_resources = rr
        if self.nodes or self.actors:
            logger.info(
                "restored GCS state: %d nodes, %d actors, %d pgs, %d jobs, "
                "%d kv entries", len(self.nodes), len(self.actors),
                len(self.placement_groups), len(self.jobs), len(self.kv))

    def resume_restored_state(self) -> None:
        """Kick schedulers for restored-but-unfinished work (call with the
        loop running)."""
        for actor in self.actors.values():
            if actor.state in (PENDING, RESTARTING):
                asyncio.get_running_loop().create_task(
                    self._schedule_actor(actor))
        for pg in self.placement_groups.values():
            if pg.state in ("PENDING", "RESCHEDULING"):
                asyncio.get_running_loop().create_task(self._schedule_pg(pg))
        restored_jobs = [j for j, job in self.jobs.items()
                         if job["state"] == "RUNNING"]
        if restored_jobs:
            asyncio.get_running_loop().create_task(
                self._reap_unattached_jobs(restored_jobs))

    async def _reap_unattached_jobs(self, job_ids: List[JobID],
                                    grace_s: float = 30.0) -> None:
        """Restored RUNNING jobs whose driver never reattaches are
        finished — preserving the driver-disconnect ⇒ job-finished
        invariant across head restarts (the driver may have died while
        the GCS was down)."""
        self._reattached_jobs: Set[JobID] = getattr(
            self, "_reattached_jobs", set())
        await asyncio.sleep(grace_s)
        for job_id in job_ids:
            job = self.jobs.get(job_id)
            if job and job["state"] == "RUNNING" and \
                    job_id not in self._reattached_jobs:
                logger.warning("job %s never reattached after GCS "
                               "restart; finishing it", job_id.hex()[:8])
                await self._finish_job(job_id)

    def _persist_actor(self, actor: ActorInfo) -> None:
        self.storage.put("actors", actor.actor_id.binary(),
                         actor.to_store())

    def _wake_actor_waiters(self, actor_id: ActorID) -> None:
        """Wake every wait_actor_alive blocked on this id; the event is
        single-use (waiters still unsatisfied re-arm a fresh one)."""
        ev = self._actor_waiters.pop(actor_id, None)
        if ev is not None:
            ev.set()

    def _persist_node(self, node: NodeInfo) -> None:
        self.storage.put("nodes", node.node_id.binary(), node.view())

    def _persist_pg(self, pg: PlacementGroupInfo) -> None:
        self.storage.put("pgs", pg.pg_id.binary(), pg.to_store())

    def _persist_job(self, job_id: JobID) -> None:
        self.storage.put("jobs", job_id.binary(), self.jobs[job_id])

    async def close(self) -> None:
        for t in self._bg:
            t.cancel()
        if self._server:
            await self._server.close()
        self.storage.close()

    def on_connection(self, conn: rpc.Connection) -> None:
        conn.on_close = self._on_disconnect

    def _on_disconnect(self, conn: rpc.Connection) -> None:
        self._server.connections.discard(conn)
        for subs in self.subscribers.values():
            subs.discard(conn)
        # Driver disconnect ⇒ job finished (reference: GcsJobManager
        # MarkJobFinished on driver exit).
        job_id = getattr(conn, "_job_id", None)
        if job_id is not None and job_id in self.jobs:
            asyncio.get_event_loop().create_task(self._finish_job(job_id))
        node_id = getattr(conn, "_node_id", None)
        if node_id is not None and node_id in self.nodes:
            asyncio.get_event_loop().create_task(
                self._fail_node(node_id, "raylet disconnected"))

    # ------------------------------------------------------------- pubsub
    async def publish(self, channel: str, data: Any) -> None:
        dead = []
        for conn in self.subscribers.get(channel, set()):
            try:
                await conn.notify("publish", {"channel": channel, "data": data})
            except Exception:
                dead.append(conn)
        for conn in dead:
            self.subscribers.get(channel, set()).discard(conn)

    async def handle_subscribe(self, data, conn) -> bool:
        self.subscribers.setdefault(data["channel"], set()).add(conn)
        return True

    async def handle_publish_logs(self, data, conn) -> None:
        """Raylet log monitors forward worker output here; fan out to
        subscribed drivers (reference: log_monitor -> driver path)."""
        await self.publish("logs", data)

    # ------------------------------------------------------------- KV
    async def handle_kv_put(self, data, conn) -> bool:
        overwrite = data.get("overwrite", True)
        key = (data["ns"], data["key"])
        if not overwrite and key in self.kv:
            return False
        self.kv[key] = data["value"]
        self.storage.put("kv", key[0] + b"\x00" + key[1], data["value"])
        return True

    async def handle_kv_get(self, data, conn):
        return self.kv.get((data["ns"], data["key"]))

    async def handle_kv_del(self, data, conn) -> bool:
        self.storage.delete("kv", data["ns"] + b"\x00" + data["key"])
        return self.kv.pop((data["ns"], data["key"]), None) is not None

    async def handle_kv_exists(self, data, conn) -> bool:
        return (data["ns"], data["key"]) in self.kv

    async def handle_kv_keys(self, data, conn) -> list:
        ns, prefix = data["ns"], data.get("prefix", b"")
        return [k for (n, k) in self.kv if n == ns and k.startswith(prefix)]

    # ------------------------------------------------------------- nodes
    async def handle_register_node(self, data, conn) -> dict:
        node_id = NodeID(data["node_id"])
        info = NodeInfo(node_id, data)
        info.conn = conn
        conn._node_id = node_id
        self.nodes[node_id] = info
        self._persist_node(info)
        # Reconcile restored actor records against the raylet's report:
        # an actor this node supposedly hosts that is NOT in its live set
        # died while the GCS was down — restart or bury it now.
        if "live_actors" in data:
            live = set(data["live_actors"])
            for actor in list(self.actors.values()):
                if actor.node_id == node_id and actor.state == ALIVE and \
                        actor.actor_id.binary() not in live:
                    await self._restart_or_kill_actor(
                        actor, "worker lost during GCS downtime")
        await self.publish("nodes", info.view())
        self._record_event(
            "gcs", "NODE_ADDED",
            f"node {node_id.hex()[:8]} registered at {info.address}",
            metadata={"node_id": node_id.hex(),
                      "resources": info.resources_total})
        logger.info("node %s registered at %s (resources=%s, slice=%r)",
                    node_id.hex()[:8], info.address, info.resources_total,
                    info.slice_id)
        return {"ok": True}

    async def handle_heartbeat(self, data, conn) -> dict:
        node_id = NodeID(data["node_id"])
        info = self.nodes.get(node_id)
        if info is None or info.state == DEAD:
            return {"ok": False}  # tells a zombie raylet to exit
        info.last_heartbeat = time.monotonic()
        fresh = data.get("resources_available", info.resources_available)
        if fresh != info.resources_available:
            info.resources_available = fresh
            ev = getattr(self, "_view_event", None)
            if ev is not None:
                ev.set()  # push the change to raylet views now
        info.pending_demands = data.get("pending_demands", [])
        return {"ok": True}

    async def handle_get_nodes(self, data, conn) -> list:
        return [n.view() for n in self.nodes.values()]

    async def handle_drain_node(self, data, conn) -> bool:
        node_id = NodeID(data["node_id"])
        await self._fail_node(node_id, "drained")
        return True

    async def _health_check_loop(self) -> None:
        period = self.config.health_check_period_ms / 1000
        timeout = period * self.config.health_check_failure_threshold
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if node.state == ALIVE and now - node.last_heartbeat > timeout:
                    await self._fail_node(node.node_id, "health check timeout")

    async def _broadcast_view_loop(self) -> None:
        """Broadcast the cluster resource view for raylet spillback decisions
        (reference: RaySyncer resource-usage gossip,
        src/ray/common/ray_syncer/ray_syncer.h:88). Event-driven: a
        heartbeat that CHANGES a node's availability triggers an
        immediate (debounced) broadcast, so spillback views are fresh
        within milliseconds of resource changes; the interval is only the
        idle fallback (injectable via resource_broadcast_interval_ms for
        deterministic tests)."""
        self._view_event = asyncio.Event()
        interval = max(self.config.resource_broadcast_interval_ms, 10) / 1000
        while True:
            self._view_event.clear()
            await self.publish("cluster_view", [
                n.view() for n in self.nodes.values() if n.state == ALIVE
            ])
            try:
                await asyncio.wait_for(self._view_event.wait(), interval)
                await asyncio.sleep(0.005)  # debounce: coalesce a burst
            except asyncio.TimeoutError:
                pass

    async def _fail_node(self, node_id: NodeID, reason: str) -> None:
        node = self.nodes.get(node_id)
        if node is None or node.state == DEAD:
            return
        node.state = DEAD
        self._persist_node(node)
        self._record_event(
            "gcs", "NODE_FAILED",
            f"node {node_id.hex()[:8]} failed: {reason}",
            severity="ERROR", metadata={"node_id": node_id.hex()})
        logger.warning("node %s failed: %s", node_id.hex()[:8], reason)
        await self.publish("nodes", node.view())
        # Restart or kill actors that lived there (reference:
        # GcsActorManager::OnNodeDead).
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in (ALIVE, PENDING,
                                                            RESTARTING):
                await self._restart_or_kill_actor(
                    actor, f"node died: {reason}")
        # Placement groups with bundles there reschedule.
        for pg in self.placement_groups.values():
            if node_id in pg.bundle_locations.values() and pg.state == "CREATED":
                pg.state = "RESCHEDULING"
                self._persist_pg(pg)
                pg.ready_event.clear()
                asyncio.get_event_loop().create_task(self._schedule_pg(pg))
        # Objects whose only copy was there are lost.
        for oid, locs in list(self.object_locations.items()):
            locs.discard(node_id.binary())

    # ------------------------------------------------------------- jobs
    async def handle_register_job(self, data, conn) -> dict:
        self._next_job += 1
        job_id = JobID.from_int(self._next_job)
        conn._job_id = job_id
        self.jobs[job_id] = {
            "state": "RUNNING",
            "driver_address": data.get("driver_address", ""),
            "start_time": time.time(),
        }
        self.storage.put("meta", b"next_job", self._next_job)
        self._persist_job(job_id)
        return {"job_id": job_id.binary()}

    async def handle_reattach_job(self, data, conn) -> dict:
        """A driver reconnecting after a GCS restart re-binds its job to
        the new connection (so driver-disconnect ⇒ job-finished still
        holds)."""
        job_id = JobID(data["job_id"])
        conn._job_id = job_id
        self._reattached_jobs = getattr(self, "_reattached_jobs", set())
        self._reattached_jobs.add(job_id)
        if job_id not in self.jobs:
            self.jobs[job_id] = {
                "state": "RUNNING",
                "driver_address": data.get("driver_address", ""),
                "start_time": time.time(),
            }
            self._persist_job(job_id)
        return {"ok": True}

    async def _finish_job(self, job_id: JobID) -> None:
        job = self.jobs.get(job_id)
        if not job or job["state"] == "FINISHED":
            return
        job["state"] = "FINISHED"
        self._record_event("gcs", "JOB_FINISHED",
                           f"job {job_id.hex()} finished",
                           metadata={"job_id": job_id.hex()})
        self.storage.delete("jobs", job_id.binary())
        await self.publish("jobs", {"job_id": job_id.binary(),
                                    "state": "FINISHED"})
        # Kill non-detached actors of the job (reference:
        # GcsActorManager::OnJobFinished).
        for actor in list(self.actors.values()):
            if actor.job_id == job_id and not actor.detached and \
                    actor.state != DEAD:
                actor.max_restarts = 0
                await self._restart_or_kill_actor(actor, "job finished")
        for pg in list(self.placement_groups.values()):
            if pg.job_id == job_id:
                await self._remove_pg(pg)

    # ------------------------------------------------------------- actors
    async def handle_register_actor(self, data, conn) -> dict:
        actor_id = ActorID(data["actor_id"])
        info = ActorInfo(actor_id, data)
        if actor_id in self._kill_tombstones:
            # kill() from ANOTHER process raced the driver's pipelined
            # registration and reached the GCS first: honor it — the
            # actor is born DEAD and never scheduled.
            self._kill_tombstones.pop(actor_id, None)
            info.state = DEAD
            info.death_cause = "killed via kill() before registration"
            self.actors[actor_id] = info
            self._persist_actor(info)
            self._wake_actor_waiters(actor_id)
            return {"ok": True}
        if info.name:
            key = (info.namespace, info.name)
            if key in self.named_actors:
                return {"ok": False,
                        "error": f"actor name {info.name!r} already taken"}
            self.named_actors[key] = actor_id
        self.actors[actor_id] = info
        self._persist_actor(info)
        self._wake_actor_waiters(actor_id)  # id now known: grace-waiters re-check
        asyncio.get_running_loop().create_task(self._schedule_actor(info))
        return {"ok": True}

    async def _schedule_actor(self, actor: ActorInfo) -> None:
        """GCS-driven actor placement (reference:
        GcsActorScheduler::ScheduleByGcs, gcs_actor_scheduler.cc:60)."""
        spec = TaskSpec.from_wire(actor.creation_task)
        # Nodes that rejected this actor with a PERMANENT config error
        # (bad runtime_env: missing container hook, unresolvable conda
        # env, …). Node-local configuration can differ (the conda root /
        # hook are raylet env vars), so only the answering node is
        # excluded; the actor dies with the real message once every
        # feasible node has permanently rejected it.
        permanent_nodes: set = set()
        permanent_error = ""
        for attempt in range(120):
            if actor.state == DEAD:
                # kill() won the race against placement: stop before
                # leasing a worker / running the user's __init__.
                return
            node = self._pick_node(spec.resources, spec.scheduling_strategy,
                                   spec.placement_group_id,
                                   spec.placement_group_bundle_index,
                                   exclude=permanent_nodes)
            if node is None:
                if permanent_nodes and self._pick_node(
                        spec.resources, spec.scheduling_strategy,
                        spec.placement_group_id,
                        spec.placement_group_bundle_index) is not None:
                    # Feasible nodes exist but ALL permanently rejected:
                    # fail now with the real error, skipping the restart
                    # policy (the config error is deterministic).
                    await self._restart_or_kill_actor(
                        actor, permanent_error or "actor creation rejected",
                        permanent=True)
                    return
                await asyncio.sleep(0.25)  # wait for resources/nodes
                continue
            try:
                reply = await node.conn.call("lease_worker_for_actor", {
                    "actor_id": actor.actor_id.binary(),
                    "task": actor.creation_task,
                }, timeout=self.config.worker_startup_timeout_s)
            except Exception as e:
                logger.warning("actor lease on %s failed: %s",
                               node.node_id.hex()[:8], e)
                await asyncio.sleep(0.25)
                continue
            if reply.get("ok"):
                if actor.state == DEAD:
                    # Killed while the lease was in flight: the worker
                    # will be refused at actor_ready and exit.
                    return
                actor.node_id = node.node_id
                self._persist_actor(actor)
                return  # worker will report actor_ready
            if reply.get("permanent"):
                permanent_nodes.add(node.node_id)
                permanent_error = reply.get("error", "")
                continue  # try remaining nodes without delay
            await asyncio.sleep(0.25)
        await self._restart_or_kill_actor(actor, "no feasible node")

    def _pick_node(self, resources: Dict[str, float],
                   strategy: Optional[dict],
                   pg_id: Optional[PlacementGroupID] = None,
                   bundle_index: int = -1,
                   exclude: Optional[set] = None) -> Optional[NodeInfo]:
        """Hybrid policy: pack onto best-utilized feasible node below the
        spread threshold, else least utilized (reference:
        hybrid_scheduling_policy.cc). `exclude` drops specific nodes
        (permanent per-node rejections)."""
        alive = [n for n in self.nodes.values() if n.state == ALIVE
                 and (not exclude or n.node_id not in exclude)]
        if strategy and strategy.get("type") == "node_affinity":
            target = NodeID(strategy["node_id"])
            for n in alive:
                if n.node_id == target:
                    return n
            return None if not strategy.get("soft") else \
                self._pick_node(resources, None)
        if pg_id is not None:
            pg = self.placement_groups.get(pg_id)
            if not pg or pg.state != "CREATED":
                return None
            if bundle_index >= 0:
                nid = pg.bundle_locations.get(bundle_index)
            else:
                nid = next(iter(pg.bundle_locations.values()), None)
            return next((n for n in alive if n.node_id == nid), None)
        feasible = [n for n in alive if _fits(resources, n.resources_available)]
        if not feasible:
            return None
        if strategy and strategy.get("type") == "spread":
            return min(feasible, key=lambda n: _utilization(n))
        feasible.sort(key=lambda n: (_utilization(n) >
                                     self.config.scheduler_spread_threshold,
                                     -_utilization(n)))
        return feasible[0]

    async def handle_actor_ready(self, data, conn) -> bool:
        actor = self.actors.get(ActorID(data["actor_id"]))
        if actor is None:
            return False
        if actor.state == DEAD:
            # kill() landed while the creation task was in flight (the
            # pipelined-registration window widens this race): do NOT
            # resurrect — tell the worker so it exits with its lease.
            return False
        actor.state = ALIVE
        actor.address = data["address"]
        actor.fast_address = data.get("fast_address", "")
        actor.node_id = NodeID(data["node_id"])
        self._persist_actor(actor)
        self._wake_actor_waiters(actor.actor_id)
        await self.publish("actors", actor.view())
        return True

    async def handle_actor_creation_failed(self, data, conn) -> bool:
        actor = self.actors.get(ActorID(data["actor_id"]))
        if actor is None:
            return False
        await self._restart_or_kill_actor(actor, data.get("error", "creation failed"))
        return True

    async def handle_report_worker_death(self, data, conn) -> bool:
        """Raylet reports a dead worker; fail any actor hosted there."""
        if data.get("worker_id"):
            self._retire_worker_metrics(data["worker_id"])
        actor_id = data.get("actor_id")
        if actor_id:
            actor = self.actors.get(ActorID(actor_id))
            if actor and actor.state in (ALIVE, PENDING):
                await self._restart_or_kill_actor(
                    actor, data.get("reason", "worker died"))
        return True

    async def _restart_or_kill_actor(self, actor: ActorInfo, reason: str,
                                     permanent: bool = False):
        """permanent=True skips the restart policy: a deterministic
        config error (bad runtime_env) recurs on every restart, so
        restarting a restartable actor would hot-loop the scheduler."""
        if not permanent and actor.max_restarts != 0 and (
                actor.max_restarts < 0 or
                actor.num_restarts < actor.max_restarts):
            actor.num_restarts += 1
            actor.state = RESTARTING
            self._persist_actor(actor)
            await self.publish("actors", actor.view())
            self._record_event(
                "gcs", "ACTOR_RESTARTED",
                f"actor {actor.actor_id.hex()[:8]} restarting "
                f"({actor.num_restarts}/{actor.max_restarts}): {reason}",
                severity="WARNING",
                metadata={"actor_id": actor.actor_id.hex()})
            logger.info("restarting actor %s (%d/%s): %s",
                        actor.actor_id.hex()[:8], actor.num_restarts,
                        actor.max_restarts, reason)
            asyncio.get_event_loop().create_task(self._schedule_actor(actor))
        else:
            actor.state = DEAD
            actor.death_cause = reason
            self._record_event(
                "gcs", "ACTOR_DEAD",
                f"actor {actor.actor_id.hex()[:8]} died: {reason}",
                severity="ERROR",
                metadata={"actor_id": actor.actor_id.hex()})
            if actor.name:
                self.named_actors.pop((actor.namespace, actor.name), None)
            if actor.detached:
                self._persist_actor(actor)  # durable tombstone
            else:
                self.storage.delete("actors", actor.actor_id.binary())
            self._wake_actor_waiters(actor.actor_id)
            await self.publish("actors", actor.view())

    async def handle_get_actor_info(self, data, conn):
        if data.get("actor_id"):
            actor = self.actors.get(ActorID(data["actor_id"]))
        else:
            key = (data.get("namespace", "default"), data["name"])
            aid = self.named_actors.get(key)
            actor = self.actors.get(aid) if aid else None
        return actor.view() if actor else None

    async def handle_wait_actor_alive(self, data, conn):
        """Block until the actor is ALIVE or DEAD (bounded by client
        timeout). Unknown ids get a short existence grace ONLY when the
        caller flags the registration as possibly in flight
        (maybe_pending): with pipelined registration, a handle can cross
        processes and reach here BEFORE the creator's fire-and-forget
        register_actor lands — only after the grace does "unknown" mean
        "does not exist". Callers that registered the actor themselves
        (and so already awaited the ack) get an immediate None for
        unknown ids; long-dead actors hit their durable DEAD tombstone
        and return immediately either way."""
        actor_id = ActorID(data["actor_id"])
        now = time.monotonic()
        deadline = now + data.get("timeout", 60.0)
        grace = 2.0 if data.get("maybe_pending") else 0.0
        exist_grace = min(now + grace, deadline)
        while True:
            actor = self.actors.get(actor_id)
            now = time.monotonic()
            if actor is None:
                if now >= exist_grace:
                    # Nonexistent id: wake (and drop) any co-waiters so
                    # the event doesn't leak for ids that never register.
                    self._wake_actor_waiters(actor_id)
                    return None
                wait_until = exist_grace
            elif actor.state in (ALIVE, DEAD):
                return actor.view()
            elif now >= deadline:
                return actor.view()
            else:
                wait_until = deadline
            # Event-driven: transitions fire _wake_actor_waiters, so the
            # answer lands one loop turn after actor_ready instead of on
            # a polling tick.
            ev = self._actor_waiters.get(actor_id)
            if ev is None:
                ev = self._actor_waiters[actor_id] = asyncio.Event()
            try:
                await asyncio.wait_for(
                    ev.wait(), max(wait_until - time.monotonic(), 0.001))
            except asyncio.TimeoutError:
                pass

    async def handle_kill_actor(self, data, conn) -> bool:
        actor = self.actors.get(ActorID(data["actor_id"]))
        if actor is None:
            # Unknown id: possibly a pipelined registration still in
            # flight from another process's handle. Tombstone it so the
            # registration (if it ever lands) is born DEAD instead of
            # leaking a running actor. Bounded: stale tombstones (ids
            # that never register) are pruned oldest-first (dict
            # preserves insertion order).
            self._kill_tombstones[ActorID(data["actor_id"])] = True
            while len(self._kill_tombstones) > 10_000:
                del self._kill_tombstones[
                    next(iter(self._kill_tombstones))]
            return False
        actor.max_restarts = 0 if data.get("no_restart", True) else actor.max_restarts
        if actor.state == ALIVE and actor.address:
            host, port = actor.address.rsplit(":", 1)
            try:
                c = await rpc.connect(host, int(port), timeout=2.0)
                await c.notify("exit_worker", {"force": True})
                await c.close()
            except Exception:
                pass
        await self._restart_or_kill_actor(actor, "killed via kill()")
        return True

    async def handle_list_actors(self, data, conn) -> list:
        return [a.view() for a in self.actors.values()]

    # ------------------------------------------------------------- placement groups
    async def handle_create_placement_group(self, data, conn) -> dict:
        pg_id = PlacementGroupID(data["pg_id"])
        pg = PlacementGroupInfo(pg_id, data)
        self.placement_groups[pg_id] = pg
        self._persist_pg(pg)
        asyncio.get_running_loop().create_task(self._schedule_pg(pg))
        return {"ok": True}

    async def handle_wait_placement_group(self, data, conn) -> dict:
        pg = self.placement_groups.get(PlacementGroupID(data["pg_id"]))
        if pg is None:
            return {"ok": False, "error": "no such placement group"}
        try:
            await asyncio.wait_for(pg.ready_event.wait(),
                                   data.get("timeout", 60.0))
        except asyncio.TimeoutError:
            return {"ok": False, "error": "timeout", "state": pg.state}
        return {"ok": pg.state == "CREATED", "state": pg.state,
                "pg": pg.view()}

    async def handle_remove_placement_group(self, data, conn) -> bool:
        pg = self.placement_groups.get(PlacementGroupID(data["pg_id"]))
        if pg:
            await self._remove_pg(pg)
        return True

    async def handle_get_pg_raylet(self, data, conn) -> dict:
        """Address of the raylet hosting a PG bundle (waits for creation) —
        used by submitters to route bundle-pinned lease requests."""
        pg = self.placement_groups.get(PlacementGroupID(data["pg_id"]))
        if pg is None:
            return {"error": "no such placement group"}
        try:
            await asyncio.wait_for(pg.ready_event.wait(),
                                   data.get("timeout", 60.0))
        except asyncio.TimeoutError:
            return {"error": f"placement group not ready: {pg.state}"}
        if pg.state != "CREATED":
            return {"error": f"placement group state: {pg.state}"}
        idx = data.get("bundle_index", -1)
        if idx < 0:
            idx = 0
        node_id = pg.bundle_locations.get(idx)
        node = self.nodes.get(node_id) if node_id else None
        if node is None or node.state != ALIVE:
            return {"error": "bundle node is not alive"}
        return {"address": node.address}

    async def handle_get_placement_group(self, data, conn):
        pg = self.placement_groups.get(PlacementGroupID(data["pg_id"]))
        return pg.view() if pg else None

    async def _remove_pg(self, pg: PlacementGroupInfo) -> None:
        pg.state = "REMOVED"
        for idx, node_id in pg.bundle_locations.items():
            node = self.nodes.get(node_id)
            if node and node.conn and node.state == ALIVE:
                try:
                    await node.conn.call("cancel_bundle", {
                        "pg_id": pg.pg_id.binary(), "bundle_index": idx})
                except Exception:
                    pass
        pg.bundle_locations.clear()
        self.placement_groups.pop(pg.pg_id, None)
        self.storage.delete("pgs", pg.pg_id.binary())

    async def _schedule_pg(self, pg: PlacementGroupInfo) -> None:
        """Two-phase bundle placement (reference:
        GcsPlacementGroupScheduler prepare/commit;
        bundle_scheduling_policy.cc PACK/SPREAD/STRICT_*). The SLICE strategy
        is TPU-native: bundles land one-per-host on a single slice's hosts,
        all-or-nothing, so an SPMD gang gets an intact ICI domain."""
        async with self._pg_lock:
            for _ in range(240):
                plan = self._plan_bundles(pg)
                if plan is not None:
                    ok = await self._prepare_commit(pg, plan)
                    if ok:
                        pg.state = "CREATED"
                        pg.bundle_locations = dict(enumerate(plan))
                        self._persist_pg(pg)
                        pg.ready_event.set()
                        await self.publish("placement_groups", pg.view())
                        return
                await asyncio.sleep(0.25)
            pg.state = "INFEASIBLE"
            self._persist_pg(pg)
            pg.ready_event.set()
            await self.publish("placement_groups", pg.view())

    def _plan_bundles(self, pg: PlacementGroupInfo) -> Optional[List[NodeID]]:
        alive = [n for n in self.nodes.values() if n.state == ALIVE]
        avail = {n.node_id: dict(n.resources_available) for n in alive}

        def take(node: NodeInfo, bundle: Dict[str, float]) -> bool:
            a = avail[node.node_id]
            if not _fits(bundle, a):
                return False
            for k, v in bundle.items():
                a[k] = a.get(k, 0) - v
            return True

        strategy = pg.strategy
        plan: List[NodeID] = []
        if strategy == "SLICE":
            # Group nodes by slice_id; need one distinct host per bundle,
            # all in the same slice.
            by_slice: Dict[str, List[NodeInfo]] = {}
            for n in alive:
                if n.slice_id:
                    by_slice.setdefault(n.slice_id, []).append(n)
            for slice_nodes in by_slice.values():
                if len(slice_nodes) < len(pg.bundles):
                    continue
                trial = []
                used = set()
                ok = True
                for bundle in pg.bundles:
                    pick = next((n for n in slice_nodes
                                 if n.node_id not in used and take(n, bundle)),
                                None)
                    if pick is None:
                        ok = False
                        break
                    used.add(pick.node_id)
                    trial.append(pick.node_id)
                if ok:
                    return trial
            return None
        if strategy in ("STRICT_SPREAD", "SPREAD"):
            used: Set[NodeID] = set()
            for bundle in pg.bundles:
                candidates = sorted(alive, key=_utilization)
                pick = next((n for n in candidates
                             if n.node_id not in used and take(n, bundle)),
                            None)
                if pick is None and strategy == "SPREAD":
                    pick = next((n for n in candidates if take(n, bundle)),
                                None)
                if pick is None:
                    return None
                used.add(pick.node_id)
                plan.append(pick.node_id)
            return plan
        # PACK / STRICT_PACK: try to fit all on one node first.
        for n in sorted(alive, key=_utilization, reverse=True):
            trial_avail = dict(n.resources_available)
            if all(_fits_take(b, trial_avail) for b in pg.bundles):
                return [n.node_id] * len(pg.bundles)
        if strategy == "STRICT_PACK":
            return None
        for bundle in pg.bundles:  # PACK fallback: fewest nodes greedy
            pick = next((n for n in sorted(alive, key=_utilization,
                                           reverse=True) if take(n, bundle)),
                        None)
            if pick is None:
                return None
            plan.append(pick.node_id)
        return plan

    async def _prepare_commit(self, pg: PlacementGroupInfo,
                              plan: List[NodeID]) -> bool:
        prepared: List[Tuple[NodeID, int]] = []
        for idx, node_id in enumerate(plan):
            node = self.nodes.get(node_id)
            try:
                r = await node.conn.call("prepare_bundle", {
                    "pg_id": pg.pg_id.binary(), "bundle_index": idx,
                    "resources": pg.bundles[idx]}, timeout=5.0)
                if not r.get("ok"):
                    raise RuntimeError(r.get("error", "prepare refused"))
                prepared.append((node_id, idx))
            except Exception as e:
                logger.info("pg prepare failed on %s: %s",
                            node_id.hex()[:8], e)
                for nid, i in prepared:
                    n2 = self.nodes.get(nid)
                    if n2 and n2.conn:
                        try:
                            await n2.conn.call("cancel_bundle", {
                                "pg_id": pg.pg_id.binary(), "bundle_index": i})
                        except Exception:
                            pass
                return False
        for (node_id, idx) in prepared:
            node = self.nodes.get(node_id)
            await node.conn.call("commit_bundle", {
                "pg_id": pg.pg_id.binary(), "bundle_index": idx})
        return True

    # ------------------------------------------------------------- object directory
    async def handle_add_object_location(self, data, conn) -> bool:
        self.object_locations.setdefault(data["object_id"], set()).add(
            data["node_id"])
        return True

    async def handle_remove_object_location(self, data, conn) -> bool:
        locs = self.object_locations.get(data["object_id"])
        if locs:
            locs.discard(data["node_id"])
        return True

    def _object_location_view(self, oid: bytes) -> dict:
        return {
            "nodes": [
                self.nodes[NodeID(n)].view()
                for n in self.object_locations.get(oid, set())
                if NodeID(n) in self.nodes and
                self.nodes[NodeID(n)].state == ALIVE
            ],
            "spilled_url": self.spilled_objects.get(oid),
        }

    async def handle_get_object_locations(self, data, conn) -> dict:
        """Single oid ('object_id') or batch ('object_ids' -> 'batch'
        list, one entry per oid in order) — N refs cost one RPC."""
        if "object_ids" in data:
            return {"batch": [self._object_location_view(o)
                              for o in data["object_ids"]]}
        return self._object_location_view(data["object_id"])

    async def handle_add_spilled_object(self, data, conn) -> bool:
        self.spilled_objects[data["object_id"]] = data["url"]
        return True

    # ------------------------------------------------------------- task events
    def _record_event(self, source: str, event_type: str, message: str,
                      severity: str = "INFO", metadata=None) -> None:
        import time as _time

        self.events.append({
            "timestamp": _time.time(), "severity": severity,
            "source": source, "event_type": event_type,
            "message": message, "pid": 0, "metadata": metadata or {}})
        if len(self.events) > 10_000:
            del self.events[:len(self.events) - 10_000]

    async def handle_report_events(self, data, conn) -> bool:
        for ev in data.get("events", []):
            self.events.append(ev)
        if len(self.events) > 10_000:
            del self.events[:len(self.events) - 10_000]
        return True

    async def handle_list_events(self, data, conn) -> list:
        limit = data.get("limit", 1000)
        return self.events[-limit:]

    async def handle_report_task_events(self, data, conn) -> bool:
        self.task_events.extend(data["events"])
        overflow = len(self.task_events) - self.config.task_events_max_buffer
        if overflow > 0:
            del self.task_events[:overflow]
        return True

    async def handle_list_task_events(self, data, conn) -> list:
        limit = data.get("limit", 1000)
        return self.task_events[-limit:]

    # ------------------------------------------------------------- metrics
    def _retire_worker_metrics(self, worker_id: bytes) -> None:
        """Fold a dead worker's counters/histograms into the persistent
        retired totals (monotonicity across worker churn); drop gauges.

        A retired worker that reports again (it was stalled, not dead)
        must NOT be double-counted: its id is remembered and later
        reports are rejected (handle_report_metrics)."""
        entry = self.worker_metrics.pop(worker_id, None)
        self.retired_worker_ids.add(worker_id)
        if not entry:
            return
        for m in entry["metrics"]:
            if m["kind"] == "gauge":
                continue
            key = (m["name"], tuple(sorted(m["tags"].items())))
            cur = self.retired_metrics.get(key)
            if cur is None:
                cur = self.retired_metrics[key] = dict(m)
                cur["bucket_counts"] = list(m.get("bucket_counts", []))
                continue
            if m["kind"] == "counter":
                cur["value"] += m["value"]
            else:
                cur["sum"] = cur.get("sum", 0) + m.get("sum", 0)
                cur["count"] = cur.get("count", 0) + m.get("count", 0)
                mine = cur["bucket_counts"]
                for i, c in enumerate(m.get("bucket_counts", [])):
                    if i < len(mine):
                        mine[i] += c
                    else:
                        mine.append(c)

    async def handle_report_metrics(self, data, conn) -> bool:
        """Latest metric snapshots per reporting worker (reference: node
        metrics agents feeding OpenCensusProxyCollector)."""
        if data["worker_id"] in self.retired_worker_ids:
            if all(m["kind"] == "gauge" for m in data["metrics"]):
                # Gauge-only reporters (e.g. raylet hardware reporters
                # that stalled through a GCS restart) can't double-count
                # anything: un-retire and accept.
                self.retired_worker_ids.discard(data["worker_id"])
            else:
                # Already folded into retired totals; accepting a new
                # snapshot would double-count its cumulative counters.
                return False
        self.worker_metrics[data["worker_id"]] = {
            "metrics": data["metrics"], "time": time.time()}
        return True

    async def handle_get_metrics(self, data, conn) -> list:
        """Aggregate across workers: counters/histograms sum, gauges take
        the latest value per tag set."""
        # Workers that stopped reporting (dead workers/nodes; healthy
        # pushers report ~2s) get their counters/histograms FOLDED into
        # the retired totals — dropping them would make aggregated
        # counters go backwards. Gauges from dead workers are dropped.
        cutoff = time.time() - 30.0
        for wid in [w for w, e in self.worker_metrics.items()
                    if e["time"] < cutoff]:
            self._retire_worker_metrics(wid)
        agg: Dict[tuple, dict] = {}
        for snap in self.retired_metrics.values():
            key = (snap["name"], tuple(sorted(snap["tags"].items())))
            cur = dict(snap)
            cur["bucket_counts"] = list(snap.get("bucket_counts", []))
            cur["_t"] = 0.0
            agg[key] = cur
        for entry in self.worker_metrics.values():
            for m in entry["metrics"]:
                key = (m["name"], tuple(sorted(m["tags"].items())))
                cur = agg.get(key)
                if cur is None:
                    cur = agg[key] = {k: v for k, v in m.items()}
                    cur["bucket_counts"] = list(
                        m.get("bucket_counts", []))
                    cur["_t"] = entry["time"]
                elif m["kind"] == "gauge":
                    # Latest report wins; _t moves only when accepted.
                    if entry["time"] >= cur["_t"]:
                        cur["value"] = m["value"]
                        cur["_t"] = entry["time"]
                elif m["kind"] == "counter":
                    cur["value"] += m["value"]
                else:
                    cur["sum"] = cur.get("sum", 0) + m.get("sum", 0)
                    cur["count"] = cur.get("count", 0) + m.get("count", 0)
                    counts = m.get("bucket_counts", [])
                    mine = cur["bucket_counts"]
                    for i, c in enumerate(counts):
                        if i < len(mine):
                            mine[i] += c
                        else:
                            mine.append(c)
        out = []
        for v in agg.values():
            v.pop("_t", None)
            out.append(v)
        return out

    # ------------------------------------------------------------- autoscaler
    async def handle_autoscaler_state(self, data, conn) -> dict:
        """Aggregate load for the autoscaler (reference:
        GcsAutoscalerStateManager / autoscaler.proto)."""
        demands: List[Dict[str, float]] = []
        nodes = []
        for n in self.nodes.values():
            if n.state != ALIVE:
                continue
            demands.extend(n.pending_demands)
            nodes.append({
                "node_id": n.node_id.binary().hex(),
                "resources_total": n.resources_total,
                "resources_available": n.resources_available,
                "slice_id": n.slice_id,
                "idle": all(
                    n.resources_available.get(k, 0) >= v
                    for k, v in n.resources_total.items()),
            })
        # Infeasible PG bundles also create demand.
        for pg in self.placement_groups.values():
            if pg.state in ("PENDING", "RESCHEDULING"):
                demands.extend(pg.bundles)
        # Standing capacity requests (reference: sdk.request_resources →
        # GcsAutoscalerStateManager cluster_resource_constraints) ride
        # SEPARATELY from task demand: they are a floor over TOTAL
        # capacity (a busy cluster already at the floor must not
        # over-scale), which the autoscaler packs against
        # resources_total, not resources_available.
        return {"pending_demands": demands, "nodes": nodes,
                "requested_bundles":
                    list(getattr(self, "_requested_resources", []))}

    async def handle_request_resources(self, data, conn) -> bool:
        """Set (REPLACE) the cluster's standing resource request
        (reference: ray.autoscaler.sdk.request_resources — each call
        overrides the previous; an empty list clears it). Persisted:
        a capacity floor must survive the head restarts it is often
        there to ride out."""
        bundles = data.get("bundles") or []
        self._requested_resources = [dict(b) for b in bundles]
        self.storage.put("meta", b"requested_resources",
                         self._requested_resources)
        return True

    # ------------------------------------------------------------- state API
    async def handle_list_object_locations(self, data, conn) -> list:
        return [{"object_id": oid.hex() if isinstance(oid, bytes) else oid,
                 "node_ids": [n.hex() for n in locs],
                 "spilled_url": self.spilled_objects.get(oid)}
                for oid, locs in self.object_locations.items()]

    async def handle_list_named_actors(self, data, conn) -> list:
        """Live named actors (reference: ray.util.list_named_actors /
        GcsActorManager::ListNamedActors). Optionally one namespace."""
        ns = data.get("namespace")
        out = []
        for (namespace, name), aid in self.named_actors.items():
            if ns is not None and namespace != ns:
                continue
            info = self.actors.get(aid)
            if info is None or info.state == DEAD:
                continue
            out.append({"name": name, "namespace": namespace,
                        "actor_id": aid.binary().hex()})
        return out

    async def handle_list_placement_groups(self, data, conn) -> list:
        return [pg.view() for pg in self.placement_groups.values()]

    async def handle_list_jobs(self, data, conn) -> list:
        return [{"job_id": jid.hex(), **info}
                for jid, info in self.jobs.items()]

    # ------------------------------------------------------------- misc
    async def handle_cluster_resources(self, data, conn) -> dict:
        total: Dict[str, float] = {}
        avail: Dict[str, float] = {}
        for n in self.nodes.values():
            if n.state != ALIVE:
                continue
            for k, v in n.resources_total.items():
                total[k] = total.get(k, 0) + v
            for k, v in n.resources_available.items():
                avail[k] = avail.get(k, 0) + v
        return {"total": total, "available": avail}

    async def handle_ping(self, data, conn) -> str:
        return "pong"


def _fits(demand: Dict[str, float], available: Dict[str, float]) -> bool:
    return all(available.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


def _fits_take(demand: Dict[str, float], available: Dict[str, float]) -> bool:
    if not _fits(demand, available):
        return False
    for k, v in demand.items():
        available[k] = available.get(k, 0) - v
    return True


def _utilization(node: NodeInfo) -> float:
    """Max over resources of used/total (critical-resource utilization)."""
    u = 0.0
    for k, total in node.resources_total.items():
        if total > 0:
            used = total - node.resources_available.get(k, 0)
            u = max(u, used / total)
    return u


def main():  # pragma: no cover - exercised via subprocess in tests
    import argparse
    import json
    import sys

    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--config", default="{}")
    p.add_argument("--persist-path", default="")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s GCS %(levelname)s %(message)s")

    async def run():
        cfg = Config.from_dict(json.loads(args.config)) if args.config != "{}" \
            else Config.from_env()
        server = GcsServer(cfg, persist_path=args.persist_path or None)
        port = await server.start(args.host, args.port)
        # Announce the bound port on stdout for the parent process.
        print(json.dumps({"port": port}), flush=True)
        await asyncio.Event().wait()

    from ray_tpu._private.profiling_hook import maybe_enable_profiler

    maybe_enable_profiler("gcs")
    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        sys.exit(0)


if __name__ == "__main__":
    main()
