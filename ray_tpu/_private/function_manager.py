"""Function manager: export/import pickled functions and actor classes.

Equivalent of the reference's FunctionActorManager
(python/ray/_private/function_manager.py): the driver exports the
cloudpickled callable to the GCS KV function table under a content-addressed
key; executors fetch + unpickle lazily by descriptor and cache.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict

import cloudpickle

from ray_tpu.core.task_spec import FunctionDescriptor

_FUNC_NS = b"fn"


class FunctionManager:
    def __init__(self, kv_put: Callable, kv_get: Callable):
        """kv_put(ns, key, value) / kv_get(ns, key) -> bytes are sync
        callables bridged to the GCS client."""
        self._kv_put = kv_put
        self._kv_get = kv_get
        self._cache: Dict[bytes, Any] = {}
        self._exported: set[bytes] = set()
        self._lock = threading.Lock()

    def export(self, fn: Callable) -> FunctionDescriptor:
        blob = cloudpickle.dumps(fn)
        key = hashlib.sha1(blob).digest()
        with self._lock:
            if key not in self._exported:
                self._kv_put(_FUNC_NS, key, blob)
                self._exported.add(key)
                self._cache[key] = fn
        return FunctionDescriptor(
            module=getattr(fn, "__module__", "") or "",
            qualname=getattr(fn, "__qualname__", repr(fn)),
            function_key=key,
        )

    def fetch(self, descriptor: FunctionDescriptor) -> Any:
        key = descriptor.function_key
        with self._lock:
            if key in self._cache:
                return self._cache[key]
        blob = self._kv_get(_FUNC_NS, key)
        return self.load(descriptor, blob)

    @staticmethod
    def _cache_key(descriptor: FunctionDescriptor):
        # Cross-language descriptors share the empty function key; cache
        # them under their importable name instead (no GCS round trip
        # per call on the fast path).
        return descriptor.function_key or (descriptor.module,
                                           descriptor.qualname)

    def get_cached(self, descriptor: FunctionDescriptor) -> Any:
        with self._lock:
            return self._cache.get(self._cache_key(descriptor))

    def load(self, descriptor: FunctionDescriptor, blob: bytes) -> Any:
        if blob is None:
            # Cross-language path (reference: cross_language.py function
            # descriptors): no pickled definition exists — resolve the
            # IMPORTABLE name instead. Same trust domain as pickled
            # functions (anything submitting tasks already runs code).
            if not descriptor.function_key and descriptor.module:
                import importlib

                obj: Any = importlib.import_module(descriptor.module)
                for part in descriptor.qualname.split("."):
                    obj = getattr(obj, part)
                with self._lock:
                    self._cache[self._cache_key(descriptor)] = obj
                return obj
            raise RuntimeError(
                f"function {descriptor.display()} not found in GCS "
                f"function table (key={descriptor.function_key.hex()})")
        fn = cloudpickle.loads(blob)
        with self._lock:
            self._cache[descriptor.function_key] = fn
        return fn
