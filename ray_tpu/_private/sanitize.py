"""Runtime sanitizer: steady-state retrace + device->host transfer gating.

The static half of graftlint (``ray_tpu/_private/lint``) catches hot-path
hazards at review time; this module catches them at *run* time.  With
``RAY_TPU_SANITIZE=1`` (or an explicit ``DecodeEngine(sanitize=True)``) the
engine builds a :class:`Sanitizer`, runs its normal warmup, then **arms**:

* **retrace counter** — the compile-cache size (``_cache_size()``) of every
  watched jitted entry point is snapshotted at arm time;
  :meth:`Sanitizer.retraces` reports any growth.  The steady decode path
  must stay at zero (the ``jit-hygiene`` lint's runtime twin).

* **transfer interposition** — the sync-forcing dunders of
  ``jax._src.array.ArrayImpl`` (``__array__``/``__bool__``/``__float__``/
  ``__int__``/``__index__``/``item``/``tolist``) are wrapped while armed.
  Any device->host pull *not* routed through the engine's ``_device_get``
  choke point (which calls :meth:`Sanitizer.expected_get`) raises
  :class:`SanitizerError` (strict mode, the default) or is tallied in
  ``unexpected_transfers``.  This works on every backend — including the
  CPU backend used by tier-1 tests, where ``jax.transfer_guard`` is a
  no-op because host-resident arrays never physically transfer.  One CPU
  nuance: ``np.asarray`` on a CPU-backend array uses the C buffer
  protocol (a zero-copy host view), so it bypasses ``__array__`` and is
  caught by the *static* host-sync lint instead; on accelerator backends
  it routes through ``__array__``/transfer-guard and is caught here too.

* **transfer guard** — ``jax_transfer_guard_device_to_host`` is additionally
  set to ``"disallow"`` while armed (belt and braces for real TPU/GPU
  backends); expected pulls run inside an ``"allow"`` scope.

Environment knobs (read by :func:`resolve`):

* ``RAY_TPU_SANITIZE=1``      — build a sanitizer when the engine doesn't pass one
* ``RAY_TPU_SANITIZE_STRICT=0`` — count unexpected transfers instead of raising
* ``RAY_TPU_SANITIZE_WARMUP=N`` — auto-arm after N engine steps (default 8)

Only one sanitizer may be armed at a time (the interposition is
process-global).  The off path costs one module-global ``is None`` check in
``_device_get`` — nothing else.
"""

from __future__ import annotations

import contextlib
import os
from typing import Callable, Dict, List, Optional

import numpy as np

ENV_SANITIZE = "RAY_TPU_SANITIZE"
ENV_STRICT = "RAY_TPU_SANITIZE_STRICT"
ENV_WARMUP = "RAY_TPU_SANITIZE_WARMUP"

DEFAULT_WARMUP_STEPS = 8

_PATCHED_ATTRS = (
    "__array__",
    "__bool__",
    "__float__",
    "__int__",
    "__index__",
    "item",
    "tolist",
)

# The process-global armed sanitizer (None = sanitizing off; the engine's
# _device_get does exactly one read of this via active()).
_ACTIVE: Optional["Sanitizer"] = None


class SanitizerError(RuntimeError):
    """An unexpected device->host transfer while the sanitizer was armed."""


def active() -> Optional["Sanitizer"]:
    return _ACTIVE


def _env_true(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).strip().lower() not in ("", "0", "false", "no")


def warmup_steps() -> int:
    try:
        return max(0, int(os.environ.get(ENV_WARMUP, DEFAULT_WARMUP_STEPS)))
    except ValueError:
        return DEFAULT_WARMUP_STEPS


def resolve(spec) -> Optional["Sanitizer"]:
    """Engine-facing constructor mirroring ``engine_trace.resolve_tracer``:

    * ``Sanitizer`` instance — used as-is
    * truthy (``True``/``1``/``"strict"``) — fresh strict sanitizer
    * ``None`` — consult ``RAY_TPU_SANITIZE`` (off unless set)
    * falsy — off
    """
    if isinstance(spec, Sanitizer):
        return spec
    if spec is None:
        if not _env_true(ENV_SANITIZE):
            return None
        return Sanitizer(strict=_env_true(ENV_STRICT, default="1"))
    if spec:
        return Sanitizer()
    return None


class Sanitizer:
    """Retrace counter + transfer interposition for one engine's hot loop."""

    def __init__(self, *, strict: bool = True, label: str = ""):
        self.strict = strict
        self.label = label
        self.armed = False
        self.expected_pulls = 0
        self.expected_async = 0
        self.unexpected_transfers: List[str] = []
        self._watched: Dict[str, Callable] = {}
        self._baseline: Dict[str, int] = {}
        self._in_expected = 0
        self._saved_attrs: Dict[str, Callable] = {}
        self._saved_guard = None
        self._guard_armed = False

    # -- watch list ---------------------------------------------------------

    def watch(self, name: str, fn) -> None:
        """Register a jitted callable for retrace accounting (idempotent;
        silently skips objects without a compile cache)."""
        if fn is None or not hasattr(fn, "_cache_size"):
            return
        self._watched[name] = fn

    # -- arm / disarm -------------------------------------------------------

    def arm(self) -> None:
        global _ACTIVE
        if self.armed:
            return
        if _ACTIVE is not None:
            raise RuntimeError(
                "another Sanitizer is already armed (the transfer "
                "interposition is process-global); disarm it first"
            )
        self._baseline = {
            name: fn._cache_size() for name, fn in self._watched.items()
        }
        self._patch_array_impl()
        self._arm_transfer_guard()
        self.armed = True
        _ACTIVE = self

    def disarm(self) -> None:
        global _ACTIVE
        if not self.armed:
            return
        self._unpatch_array_impl()
        self._disarm_transfer_guard()
        self.armed = False
        if _ACTIVE is self:
            _ACTIVE = None

    def __enter__(self) -> "Sanitizer":
        self.arm()
        return self

    def __exit__(self, *exc) -> None:
        self.disarm()

    # -- the expected choke point ------------------------------------------

    def expected_get(self, x) -> np.ndarray:
        """The sanctioned blocking pull (engine ``_device_get`` routes here)."""
        self._in_expected += 1
        try:
            with self._allow_guard():
                out = np.asarray(x)
        finally:
            self._in_expected -= 1
        self.expected_pulls += 1
        return out

    def expected_copy_async(self, x) -> None:
        """The sanctioned async host copy (engine dispatch ring)."""
        self._in_expected += 1
        try:
            with self._allow_guard():
                try:
                    x.copy_to_host_async()
                except AttributeError:
                    pass
        finally:
            self._in_expected -= 1
        self.expected_async += 1

    # -- accounting ---------------------------------------------------------

    def retraces(self) -> Dict[str, int]:
        """Watched functions whose compile cache grew since arm()."""
        out: Dict[str, int] = {}
        for name, fn in self._watched.items():
            base = self._baseline.get(name)
            if base is None:
                continue
            delta = fn._cache_size() - base
            if delta:
                out[name] = delta
        return out

    def total_retraces(self) -> int:
        return sum(self.retraces().values())

    def stats(self) -> Dict[str, object]:
        return {
            "armed": self.armed,
            "strict": self.strict,
            "expected_pulls": self.expected_pulls,
            "expected_async": self.expected_async,
            "unexpected_transfers": len(self.unexpected_transfers),
            "retraces": self.retraces(),
            "watched": sorted(self._watched),
        }

    # -- interposition ------------------------------------------------------

    def _on_transfer(self, kind: str) -> None:
        if self._in_expected:
            return
        msg = (
            f"unexpected device->host transfer via ArrayImpl.{kind} while the "
            f"sanitizer was armed{(' (' + self.label + ')') if self.label else ''}; "
            "hot-path pulls must route through _device_get"
        )
        self.unexpected_transfers.append(msg)
        if self.strict:
            raise SanitizerError(msg)

    def _patch_array_impl(self) -> None:
        cls = _array_impl_class()
        if cls is None:
            return
        for attr in _PATCHED_ATTRS:
            orig = getattr(cls, attr, None)
            if orig is None:
                continue
            self._saved_attrs[attr] = orig

            def _make(orig=orig, attr=attr):
                def _guarded(arr, *args, **kwargs):
                    san = _ACTIVE
                    if san is not None:
                        san._on_transfer(attr)
                    return orig(arr, *args, **kwargs)

                return _guarded

            setattr(cls, attr, _make())

    def _unpatch_array_impl(self) -> None:
        cls = _array_impl_class()
        if cls is None:
            return
        for attr, orig in self._saved_attrs.items():
            setattr(cls, attr, orig)
        self._saved_attrs.clear()

    # -- transfer guard (no-op on the CPU backend, real on TPU/GPU) ---------

    def _arm_transfer_guard(self) -> None:
        try:
            import jax

            self._saved_guard = jax.config.jax_transfer_guard_device_to_host
            jax.config.update("jax_transfer_guard_device_to_host", "disallow")
            self._guard_armed = True
        except Exception:
            self._guard_armed = False

    def _disarm_transfer_guard(self) -> None:
        if not self._guard_armed:
            return
        try:
            import jax

            jax.config.update(
                "jax_transfer_guard_device_to_host", self._saved_guard
            )
        except Exception:
            pass
        self._guard_armed = False
        self._saved_guard = None

    def _allow_guard(self):
        if not self._guard_armed:
            return contextlib.nullcontext()
        try:
            import jax

            return jax.transfer_guard_device_to_host("allow")
        except Exception:
            return contextlib.nullcontext()


def _array_impl_class():
    try:
        from jax._src.array import ArrayImpl

        return ArrayImpl
    except Exception:
        return None
