"""GCS table storage — write-through persistence for the control plane.

Equivalent of the reference's GcsTableStorage over a StoreClient
(src/ray/gcs/gcs_server/gcs_table_storage.h, src/ray/gcs/store_client/):
every mutation of a GCS table is written through to durable storage so a
restarted GCS process recovers the cluster's control state (actors, nodes,
jobs, placement groups, internal KV) — the reference's Redis-backed head
fault tolerance, here on sqlite (one file under the session dir, WAL mode,
no extra process).
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Dict, Iterator, Optional, Tuple

import msgpack


class GcsTableStorage:
    """Keyed blob tables with write-through semantics.

    Values are msgpack-encoded (bytes/str/int/float/dict/list only —
    exactly the wire types GCS state is built from).
    """

    def __init__(self, path: Optional[str]):
        # path=None → volatile (in-memory sqlite): same code path, no
        # durability — used when persistence is disabled.
        self._db = sqlite3.connect(path or ":memory:",
                                   check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS gcs_tables ("
            " tab TEXT NOT NULL, key BLOB NOT NULL, value BLOB NOT NULL,"
            " PRIMARY KEY (tab, key))")
        self._db.commit()
        self._lock = threading.Lock()

    def put(self, table: str, key: bytes, value) -> None:
        blob = msgpack.packb(value, use_bin_type=True)
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO gcs_tables (tab, key, value) "
                "VALUES (?, ?, ?)", (table, key, blob))
            self._db.commit()

    def get(self, table: str, key: bytes):
        with self._lock:
            row = self._db.execute(
                "SELECT value FROM gcs_tables WHERE tab = ? AND key = ?",
                (table, key)).fetchone()
        if row is None:
            return None
        return msgpack.unpackb(row[0], raw=False)

    def delete(self, table: str, key: bytes) -> None:
        with self._lock:
            self._db.execute(
                "DELETE FROM gcs_tables WHERE tab = ? AND key = ?",
                (table, key))
            self._db.commit()

    def load_all(self, table: str) -> Iterator[Tuple[bytes, object]]:
        with self._lock:
            rows = self._db.execute(
                "SELECT key, value FROM gcs_tables WHERE tab = ?",
                (table,)).fetchall()
        for key, blob in rows:
            yield key, msgpack.unpackb(blob, raw=False)

    def close(self) -> None:
        with self._lock:
            self._db.close()
