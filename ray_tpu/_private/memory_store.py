"""In-process memory store for small/inline objects.

Equivalent of the reference's CoreWorkerMemoryStore
(src/ray/core_worker/store_provider/memory_store/memory_store.h:43): small
objects (< max_direct_call_object_size) live in the owner's process and are
inlined into task replies instead of round-tripping through shared memory.
Waiters come in two flavors: asyncio futures (loop-side getters) and
threading.Events (the synchronous fast path in worker.get, which reads the
store directly from the user thread without an io-loop round trip).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Dict, List, Optional

from ray_tpu.core.ids import ObjectID

IN_PLASMA = object()  # sentinel: value lives in the shm store


class MemoryStore:
    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._objects: Dict[ObjectID, bytes] = {}
        self._plasma_markers: set[ObjectID] = set()
        self._waiters: Dict[ObjectID, List[asyncio.Future]] = {}
        # Cross-thread waiters (worker.get fast path). Guarded by _sync_lock;
        # _objects itself is written only on the loop thread and read from
        # any thread (GIL-atomic dict ops).
        self._sync_lock = threading.Lock()
        self._sync_waiters: Dict[ObjectID, List[threading.Event]] = {}

    def put(self, object_id: ObjectID, data: bytes) -> None:
        """Store serialized bytes and wake waiters. Thread-safe via loop."""
        self._loop.call_soon_threadsafe(self._put_in_loop, object_id, data)

    def _put_in_loop(self, object_id: ObjectID, data) -> None:
        if data is IN_PLASMA:
            self._plasma_markers.add(object_id)
        else:
            self._objects[object_id] = data
        for fut in self._waiters.pop(object_id, []):
            if not fut.done():
                fut.set_result(True)
        if self._sync_waiters:
            with self._sync_lock:
                events = self._sync_waiters.pop(object_id, ())
            for ev in events:
                ev.set()

    def put_in_loop(self, object_id: ObjectID, data: bytes) -> None:
        """Same as put() but caller is already on the loop."""
        self._put_in_loop(object_id, data)

    def put_sync(self, object_id: ObjectID, data) -> None:
        """Store from a non-loop thread WITHOUT a loop round trip (the
        fastlane reply pump): dict writes are GIL-atomic, synchronous
        waiters are woken directly, and loop-side futures (if any) are
        woken via one call_soon_threadsafe — paid only when an async
        getter is actually parked on this object."""
        if data is IN_PLASMA:
            self._plasma_markers.add(object_id)
        else:
            self._objects[object_id] = data
        if self._sync_waiters:
            with self._sync_lock:
                events = self._sync_waiters.pop(object_id, ())
            for ev in events:
                ev.set()
        if object_id in self._waiters and self._loop.is_running():
            self._loop.call_soon_threadsafe(self._wake_async, object_id)

    def _wake_async(self, object_id: ObjectID) -> None:
        for fut in self._waiters.pop(object_id, []):
            if not fut.done():
                fut.set_result(True)

    def mark_in_plasma_sync(self, object_id: ObjectID) -> None:
        self.put_sync(object_id, IN_PLASMA)

    def mark_in_plasma(self, object_id: ObjectID) -> None:
        self._loop.call_soon_threadsafe(self._put_in_loop, object_id, IN_PLASMA)

    def mark_in_plasma_in_loop(self, object_id: ObjectID) -> None:
        """Synchronous marker set (caller on the loop): out-of-scope
        decisions race the marker, so reply processing must not defer it."""
        self._put_in_loop(object_id, IN_PLASMA)

    def get_if_exists(self, object_id: ObjectID) -> Optional[bytes]:
        return self._objects.get(object_id)

    def is_in_plasma(self, object_id: ObjectID) -> bool:
        return object_id in self._plasma_markers

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self._objects or object_id in self._plasma_markers

    async def wait_ready(self, object_id: ObjectID,
                         timeout: Optional[float] = None) -> bool:
        """Wait until the object is in this store or marked in-plasma."""
        if self.contains(object_id):
            return True
        fut = asyncio.get_running_loop().create_future()
        self._waiters.setdefault(object_id, []).append(fut)
        if self.contains(object_id):
            # Landed between the check and registration: a cross-thread
            # put_sync saw no waiter entry, so nobody will wake us.
            lst = self._waiters.get(object_id)
            if lst and fut in lst:
                lst.remove(fut)
            return True
        try:
            await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            lst = self._waiters.get(object_id)
            if lst and fut in lst:
                lst.remove(fut)

    def wait_ready_sync(self, object_id: ObjectID,
                        timeout: Optional[float] = None) -> bool:
        """Block the calling (non-loop) thread until the object lands.

        Used by the synchronous get fast path: avoids two cross-thread
        hops per get by waiting on a threading.Event set directly from
        _put_in_loop.
        """
        if self.contains(object_id):
            return True
        ev = threading.Event()
        with self._sync_lock:
            self._sync_waiters.setdefault(object_id, []).append(ev)
        try:
            if self.contains(object_id):  # landed during registration
                return True
            return ev.wait(timeout)
        finally:
            with self._sync_lock:
                lst = self._sync_waiters.get(object_id)
                if lst is not None:
                    try:
                        lst.remove(ev)
                    except ValueError:
                        pass
                    if not lst:
                        del self._sync_waiters[object_id]

    def delete(self, object_id: ObjectID) -> None:
        self._objects.pop(object_id, None)
        self._plasma_markers.discard(object_id)

    def fail(self, object_id: ObjectID, error_bytes: bytes) -> None:
        """Store an error envelope (raised on get)."""
        self.put(object_id, error_bytes)

    def size(self) -> int:
        return len(self._objects) + len(self._plasma_markers)
