"""Pluggable external storage for object spilling.

Reference: python/ray/_private/external_storage.py:72 (ExternalStorage
interface), :233 (FileSystemStorage), :296 (ExternalStorageSmartOpenImpl
for cloud URIs). A TPU pod's host RAM overflow needs somewhere durable:
the raylet spills through one of these backends, keyed by the URI scheme
of ``object_spilling_path`` (bare paths and file:// -> local filesystem;
any other scheme -> fsspec when available, or a registered plugin).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, Type
from urllib.parse import urlparse

logger = logging.getLogger(__name__)


class ExternalStorage:
    """One spill backend. URLs returned by put() are cluster-global."""

    def put(self, key: str, data: bytes) -> str:
        """Write data; returns the restore URL."""
        raise NotImplementedError

    def get(self, url: str) -> bytes:
        raise NotImplementedError

    def delete(self, url: str) -> None:
        raise NotImplementedError


class FileSystemStorage(ExternalStorage):
    """Spill to a local/NFS directory (reference: FileSystemStorage)."""

    def __init__(self, base_dir: str):
        self.base_dir = base_dir

    def put(self, key: str, data: bytes) -> str:
        os.makedirs(self.base_dir, exist_ok=True)
        path = os.path.join(self.base_dir, key)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # torn-write safety
        return path

    def get(self, url: str) -> bytes:
        with open(url, "rb") as f:
            return f.read()

    def delete(self, url: str) -> None:
        try:
            os.unlink(url)
        except OSError:
            pass


class FsspecStorage(ExternalStorage):
    """Any fsspec-resolvable URI (s3://bucket/prefix, gs://...).

    Gated on fsspec availability (hermetic images may lack it);
    construction raises ImportError otherwise."""

    def __init__(self, base_uri: str):
        import fsspec  # noqa: F401 — availability gate

        self.base_uri = base_uri.rstrip("/")

    def _fs(self, uri: str):
        import fsspec

        return fsspec.core.url_to_fs(uri)

    def put(self, key: str, data: bytes) -> str:
        uri = f"{self.base_uri}/{key}"
        fs, path = self._fs(uri)
        with fs.open(path, "wb") as f:
            f.write(data)
        return uri

    def get(self, url: str) -> bytes:
        fs, path = self._fs(url)
        with fs.open(path, "rb") as f:
            return f.read()

    def delete(self, url: str) -> None:
        try:
            fs, path = self._fs(url)
            fs.rm(path)
        except Exception:
            pass


_SCHEME_REGISTRY: Dict[str, Type[ExternalStorage]] = {}


def register_storage(scheme: str, cls: Type[ExternalStorage]) -> None:
    """Plugin hook: map a URI scheme to a storage backend class
    (constructed with the full base URI). Tests register mock remotes."""
    _SCHEME_REGISTRY[scheme] = cls


def _load_env_plugins() -> None:
    """RAY_TPU_SPILL_PLUGINS="scheme=module:ClassName,..." — lets every
    process in the cluster (notably raylets, which are separate
    processes) resolve custom spill schemes (reference: the
    object_spilling_config JSON passed through ray_config)."""
    spec = os.environ.get("RAY_TPU_SPILL_PLUGINS", "")
    for part in spec.split(","):
        if "=" not in part:
            continue
        scheme, target = part.split("=", 1)
        scheme = scheme.strip()
        if scheme in _SCHEME_REGISTRY:
            continue
        try:
            import importlib

            mod_name, _, attr = target.partition(":")
            mod = importlib.import_module(mod_name.strip())
            _SCHEME_REGISTRY[scheme] = getattr(mod, attr.strip())
        except Exception as e:
            # A typo here would otherwise silently fall through to
            # FsspecStorage and nothing would spill under pressure.
            logger.warning(
                "spill plugin %r (%s) failed to load: %s: %s",
                scheme, target.strip(), type(e).__name__, e)


def storage_for_path(path: str) -> ExternalStorage:
    """Resolve the spill backend for a configured spilling path/URI."""
    scheme = urlparse(path).scheme
    if scheme in ("", "file"):
        base = path[len("file://"):] if path.startswith("file://") else path
        return FileSystemStorage(base)
    if scheme not in _SCHEME_REGISTRY:
        _load_env_plugins()
    if scheme in _SCHEME_REGISTRY:
        return _SCHEME_REGISTRY[scheme](path)
    return FsspecStorage(path)


def storage_scheme(url: str) -> str:
    return urlparse(url).scheme
