"""Raylet — per-node daemon: worker pool, local scheduler, object manager.

Equivalent of the reference's raylet (src/ray/raylet/node_manager.h:119):
- WorkerPool with prestart and dedicated actor workers
  (src/ray/raylet/worker_pool.h:159,:425).
- Local task manager: worker-lease queue + resource accounting + spillback
  to other raylets (src/ray/raylet/scheduling/cluster_task_manager.cc:44,
  local_task_manager.cc); hybrid policy — pack until the critical-resource
  utilization threshold, then spread.
- Placement-group bundle bookkeeping with 2PC prepare/commit
  (src/ray/raylet/placement_group_resource_manager.h).
- Object manager: cross-node chunked pull/push riding the RPC plane
  (src/ray/object_manager/object_manager.cc, pull_manager.cc), spilling to
  local disk with GCS-recorded URLs (src/ray/raylet/local_object_manager.h).

TPU-native: the node registers its slice identity (slice_id/topology) so the
GCS can gang-schedule SLICE placement groups; TPU chips are normal resources
("TPU": chips) with visibility plumbed to workers via env vars.
"""

from __future__ import annotations

import asyncio
import logging
import os
import signal
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.core import rpc
from ray_tpu.core.config import Config
from ray_tpu.core.ids import NodeID, ObjectID, WorkerID
from ray_tpu.core.shm_client import ShmClient, StoreFullError

logger = logging.getLogger(__name__)

CHUNK = 4 << 20


class WorkerHandle:
    def __init__(self, worker_id: WorkerID, pid: int, proc=None):
        self.worker_id = worker_id
        self.pid = pid
        self.proc = proc
        self.address: str = ""
        self.fast_address: str = ""  # fastlane (native task path) port
        self.conn: Optional[rpc.Connection] = None
        self.registered = asyncio.Event()
        self.state = "starting"  # starting|idle|leased|actor|dead
        self.lease_id: Optional[bytes] = None
        self.actor_id: Optional[bytes] = None
        self.job_id: Optional[bytes] = None
        self.log_path: Optional[str] = None
        self.log_offset: int = 0
        self.log_partial: bytes = b""
        self.tpu = False  # spawned with the TPU plugin env
        self.kill_requested = False  # kill arrived before spawn landed
        self.forked = False  # forkserver child (tracked by pid, not proc)

    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        if self.forked and self.pid:
            try:
                os.kill(self.pid, 0)
                return True
            except OSError:
                return False
        return True  # spawn still in flight / driver: liveness via conn

    def terminate(self) -> None:
        if self.proc is not None:
            if self.proc.poll() is None:
                self.proc.terminate()
        elif self.forked and self.pid:
            try:
                os.kill(self.pid, signal.SIGTERM)
            except OSError:
                pass


class LeaseRequest:
    def __init__(self, data: dict):
        self.lease_id: bytes = data["lease_id"]
        self.resources: Dict[str, float] = data.get("resources", {})
        self.pg_id: Optional[bytes] = data.get("pg_id")
        self.pg_bundle: int = data.get("pg_bundle", -1)
        self.job_id: Optional[bytes] = data.get("job_id")
        self.grant_fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self.num_spillbacks: int = data.get("num_spillbacks", 0)


class Raylet:
    def __init__(self, node_id: NodeID, gcs_address: str, store_path: str,
                 resources: Dict[str, float], config: Config,
                 session_dir: str, labels: Optional[Dict[str, str]] = None,
                 slice_id: str = ""):
        self.node_id = node_id
        self.gcs_address = gcs_address
        self.store_path = store_path
        self.resources_total = dict(resources)
        self.available = dict(resources)
        self.config = config
        self.session_dir = session_dir
        self.labels = labels or {}
        self.slice_id = slice_id

        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self.idle_workers: List[WorkerHandle] = []
        self.lease_queue: List[LeaseRequest] = []
        self.leases: Dict[bytes, Tuple[WorkerHandle, Dict[str, float],
                                       Optional[Tuple[bytes, int]]]] = {}
        # (pg_id, bundle_index) -> {"reserved": res, "available": res, "committed": bool}
        self.bundles: Dict[Tuple[bytes, int], dict] = {}
        self.cluster_view: List[dict] = []
        self.gcs: Optional[rpc.Connection] = None
        self.store: Optional[ShmClient] = None
        self._server: Optional[rpc.Server] = None
        self._bg: List[asyncio.Task] = []
        self._spilled_local: Dict[bytes, str] = {}
        self._spill_backend = None
        self._pulls_inflight: Dict[bytes, asyncio.Future] = {}
        self._spawn_tasks: Set[asyncio.Task] = set()
        self.address = ""
        self.dead = False
        # Forkserver (zygote) worker factory: one warm template process;
        # CPU workers fork from it in ~10ms instead of a fresh
        # interpreter + import chain (reference: worker_pool.h:359,:425).
        self._forkserver: Optional[subprocess.Popen] = None
        self._fork_lock = threading.Lock()  # serializes the pipe protocol

    # ------------------------------------------------------------- lifecycle
    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self.store = ShmClient(self.store_path)
        # Background arena pre-population: first-touch tmpfs page faults
        # move off the first puts' critical path.
        self.store.prefault()
        self._server = rpc.Server(self, host, port)
        port = await self._server.start()
        self.address = f"{host}:{port}"
        ghost, gport = self.gcs_address.rsplit(":", 1)
        self.gcs = await rpc.connect(ghost, int(gport),
                                     handler=self._on_gcs_message,
                                     name="raylet->gcs")
        self.gcs.on_close = self._on_gcs_close
        # Native object-transfer server: bulk object bytes move
        # store-to-store over raw TCP (C++ threads), Python only
        # coordinates (reference: ObjectManager's dedicated rpc service).
        try:
            from ray_tpu.core.transfer_client import TransferServer

            self.transfer_server = TransferServer(self.store_path)
            transfer_port = self.transfer_server.port
        except Exception:
            logger.exception("native transfer server failed to start; "
                             "falling back to rpc chunk transfer")
            self.transfer_server = None
            transfer_port = 0
        self._transfer_port = transfer_port
        await self._register_with_gcs(self.gcs)
        self._bg.append(asyncio.get_event_loop().create_task(self._heartbeat_loop()))
        self._bg.append(asyncio.get_event_loop().create_task(self._reap_loop()))
        self._bg.append(asyncio.get_event_loop().create_task(
            self._log_monitor_loop()))
        self._bg.append(asyncio.get_event_loop().create_task(self._spill_loop()))
        self._bg.append(asyncio.get_event_loop().create_task(
            self._reporter_loop()))
        self._bg.append(asyncio.get_event_loop().create_task(self._drain_loop()))
        if self.config.memory_monitor_refresh_ms > 0:
            self._bg.append(asyncio.get_event_loop().create_task(
                self._memory_monitor_loop()))
        logger.info("raylet %s on %s resources=%s",
                    self.node_id.hex()[:8], self.address, self.resources_total)
        self._maybe_refill_pool()  # prestart the standing worker pool
        return port

    async def close(self) -> None:
        self.dead = True
        for t in self._bg:
            t.cancel()
        if self._spawn_tasks:
            # Let in-flight spawns land so their processes get a proc
            # handle (finish_spawn terminates them when self.dead).
            await asyncio.gather(*list(self._spawn_tasks),
                                 return_exceptions=True)
        for w in self.workers.values():
            w.terminate()
        if self._forkserver is not None and self._forkserver.poll() is None:
            self._forkserver.terminate()
        if getattr(self, "transfer_server", None) is not None:
            await asyncio.get_event_loop().run_in_executor(
                None, self.transfer_server.stop)
        if self._server:
            await self._server.close()
        if self.gcs:
            await self.gcs.close()
        # The shm store stays mapped until process exit: executor-thread
        # work (spill IO, log readers) may still be in flight and a call
        # through a freed store handle segfaults (see core_worker
        # disconnect). The raylet process is exiting anyway.

    async def _register_with_gcs(self, conn: rpc.Connection) -> None:
        await conn.call("register_node", {
            "node_id": self.node_id.binary(),
            "address": self.address,
            "hostname": os.uname().nodename,
            "store_path": self.store_path,
            "resources": self.resources_total,
            "labels": self.labels,
            "slice_id": self.slice_id,
            "transfer_port": self._transfer_port,
            # Live actors hosted here: a restarted GCS reconciles its
            # restored actor table against this (an actor that died
            # during GCS downtime must not stay ALIVE forever).
            "live_actors": [w.actor_id for w in self.workers.values()
                            if w.actor_id and w.state != "dead"],
        })
        await conn.call("subscribe", {"channel": "cluster_view"})
        await conn.call("subscribe", {"channel": "jobs"})

    def _on_gcs_close(self, conn: rpc.Connection) -> None:
        if not self.dead:
            asyncio.get_event_loop().create_task(self._reconnect_gcs())

    async def _reconnect_gcs(self) -> None:
        """The GCS died: reconnect and re-register under the same node id
        once it is back (reference: raylets buffer through GCS restarts —
        HandleNotifyGCSRestart, node_manager.h:614). Workers keep running
        throughout; only control-plane calls stall."""
        ghost, gport = self.gcs_address.rsplit(":", 1)
        deadline = time.monotonic() + self.config.gcs_down_exit_s
        while not self.dead:
            conn = None
            try:
                conn = await rpc.connect(ghost, int(gport),
                                         handler=self._on_gcs_message,
                                         name="raylet->gcs")
                await self._register_with_gcs(conn)
            except Exception:
                if conn is not None:
                    await conn.close()
                if time.monotonic() > deadline:
                    logger.error("GCS unreachable for %.0fs; exiting",
                                 self.config.gcs_down_exit_s)
                    os._exit(1)
                await asyncio.sleep(0.5)
                continue
            conn.on_close = self._on_gcs_close
            self.gcs = conn
            logger.info("re-registered with restarted GCS")
            return

    async def _on_gcs_message(self, method: str, data, conn):
        if method == "publish":
            channel = data["channel"]
            if channel == "cluster_view":
                self.cluster_view = data["data"]
            elif channel == "jobs" and data["data"].get("state") == "FINISHED":
                await self._on_job_finished(data["data"]["job_id"])
            return None
        # The GCS issues RPCs (actor leases, bundle 2PC) back over this
        # connection; dispatch them to the same handlers the server exposes.
        fn = getattr(self, "handle_" + method, None)
        if fn is None:
            raise rpc.RpcError(f"unknown method {method}")
        return await fn(data, conn)

    async def _on_job_finished(self, job_id: bytes) -> None:
        for w in list(self.workers.values()):
            if w.job_id == job_id and w.state == "leased":
                await self._kill_worker(w, "job finished")

    def _notify_resources_changed(self) -> None:
        """Event-driven resource sync (reference: RaySyncer,
        ray_syncer.h:88 — resource deltas push immediately instead of
        waiting out the periodic report): wakes the heartbeat loop so
        other raylets' spillback views refresh within milliseconds of a
        grant/release rather than a full period later."""
        ev = getattr(self, "_hb_event", None)
        if ev is not None:
            ev.set()

    async def _heartbeat_loop(self) -> None:
        self._hb_event = asyncio.Event()
        while not self.dead:
            # Clear BEFORE reading self.available: a change landing while
            # the call is in flight re-arms the event and triggers an
            # immediate follow-up heartbeat.
            self._hb_event.clear()
            try:
                r = await self.gcs.call("heartbeat", {
                    "node_id": self.node_id.binary(),
                    "resources_available": self.available,
                    # Queued lease demands feed the autoscaler (reference:
                    # resource-load piggybacked on raylet heartbeats and
                    # aggregated by GcsAutoscalerStateManager).
                    "pending_demands": [
                        req.resources for req in self.lease_queue[:100]],
                }, timeout=5.0)
                if not r.get("ok"):
                    logger.error("GCS declared this node dead; exiting")
                    os._exit(1)
            except Exception:
                if self.dead:
                    return
            await asyncio.sleep(0.01)  # min gap: bounds event-driven rate
            try:
                await asyncio.wait_for(
                    self._hb_event.wait(),
                    min(self.config.health_check_period_ms / 2, 100) / 1000)
            except asyncio.TimeoutError:
                pass

    async def _reporter_loop(self) -> None:
        """Per-node hardware reporter (reference:
        python/ray/dashboard/modules/reporter/ — per-node cpu/mem/device
        stats flowing into the metrics pipeline): cpu%, memory, object
        store usage, and TPU chip allocation as gauges tagged with this
        node, surfaced at the dashboard's /metrics and /api/node_stats."""
        period = 2.0
        prev_cpu: Optional[Tuple[float, float]] = None
        tags = {"node_id": self.node_id.hex(),
                "hostname": os.uname().nodename}
        while not self.dead:
            await asyncio.sleep(period)
            try:
                gauges = []

                def g(name, value, desc):
                    gauges.append({"name": name, "kind": "gauge",
                                   "value": float(value), "tags": tags,
                                   "description": desc})

                # cpu utilisation from /proc/stat deltas
                with open("/proc/stat") as f:
                    parts = f.readline().split()[1:]
                vals = [float(x) for x in parts]
                total, idle = sum(vals), vals[3] + (
                    vals[4] if len(vals) > 4 else 0.0)
                if prev_cpu is not None:
                    dt, di = total - prev_cpu[0], idle - prev_cpu[1]
                    if dt > 0:
                        g("node.cpu_percent", 100.0 * (1 - di / dt),
                          "node CPU utilisation")
                prev_cpu = (total, idle)
                mem = {}
                with open("/proc/meminfo") as f:
                    for line in f:
                        k, v = line.split(":", 1)
                        mem[k] = float(v.split()[0]) * 1024
                g("node.mem_total_bytes", mem.get("MemTotal", 0),
                  "node memory total")
                g("node.mem_available_bytes", mem.get("MemAvailable", 0),
                  "node memory available")
                if self.store is not None:
                    st = self.store.stats()
                    g("node.object_store_used_bytes",
                      st.get("bytes_used", 0), "plasma bytes used")
                    g("node.object_store_capacity_bytes",
                      st.get("capacity", 0), "plasma capacity")
                    g("node.object_store_num_objects",
                      st.get("num_objects", 0), "plasma object count")
                tpu_total = self.resources_total.get("TPU", 0.0)
                if tpu_total:
                    g("node.tpu_total", tpu_total, "TPU chips on node")
                    g("node.tpu_available",
                      self.available.get("TPU", 0.0),
                      "unallocated TPU chips")
                if self.gcs and not self.gcs.closed:
                    await self.gcs.call("report_metrics", {
                        "worker_id": b"raylet:" + self.node_id.binary(),
                        "metrics": gauges})
            except asyncio.CancelledError:
                return
            except Exception:
                logger.debug("hardware reporter tick failed",
                             exc_info=True)

    async def _memory_monitor_loop(self) -> None:
        """Kill the newest leased worker when node memory crosses the
        threshold (reference: MemoryMonitor + retriable-FIFO policy) —
        shed load before the kernel OOM killer shoots the raylet."""
        from ray_tpu._private.memory_monitor import (memory_usage_fraction,
                                                     pick_worker_to_kill)

        period = self.config.memory_monitor_refresh_ms / 1000.0
        while not self.dead:
            await asyncio.sleep(period)
            try:
                frac = memory_usage_fraction()
                if frac <= self.config.memory_usage_threshold:
                    continue
                victim = pick_worker_to_kill(self.workers.values())
                if victim is None:
                    continue
                logger.warning(
                    "memory usage %.1f%% > %.1f%%: killing worker %s "
                    "(its task will retry)", frac * 100,
                    self.config.memory_usage_threshold * 100,
                    victim.worker_id.hex()[:12])
                try:
                    from ray_tpu.util.events import make_event

                    await self.gcs.call("report_events", {"events": [
                        make_event("raylet", "WORKER_OOM_KILLED",
                                   f"worker {victim.worker_id.hex()[:8]} "
                                   f"killed at {frac:.0%} memory usage",
                                   severity="WARNING",
                                   metadata={"node_id":
                                             self.node_id.hex()})]})
                except Exception:
                    pass
                await self._kill_worker(
                    victim, f"node OOM: memory usage {frac:.2%}")
            except Exception:
                logger.exception("memory monitor iteration failed")

    async def _drain_loop(self) -> None:
        """Periodic queue re-evaluation (cluster view changes over time)."""
        while not self.dead:
            await asyncio.sleep(0.2)
            if self.lease_queue:
                self._drain_queue()

    async def _reap_loop(self) -> None:
        """Monitor spawned worker processes; report deaths."""
        while not self.dead:
            await asyncio.sleep(0.2)
            for w in list(self.workers.values()):
                if (w.proc is not None or w.forked) and \
                        w.state != "dead" and not w.alive():
                    await self._on_worker_death(w)

    async def _on_worker_death(self, w: WorkerHandle) -> None:
        if w.state == "dead":
            return  # reap loop and conn-close can both observe the death
        prev_state = w.state
        w.state = "dead"
        self.workers.pop(w.worker_id, None)
        if w in self.idle_workers:
            self.idle_workers.remove(w)
        if w.lease_id and w.lease_id in self.leases:
            _, res, bundle_key = self.leases.pop(w.lease_id)
            self._release_resources(res, bundle_key)
        if prev_state == "actor":
            try:
                await self.gcs.call("report_worker_death", {
                    "actor_id": w.actor_id,
                    "reason": f"worker process {w.pid} exited",
                })
            except Exception:
                pass
        logger.info("worker %s (pid=%s, state=%s) died",
                    w.worker_id.hex()[:8], w.pid, prev_state)
        self._drain_queue()

    async def _kill_worker(self, w: WorkerHandle, reason: str) -> None:
        logger.info("killing worker %s: %s", w.worker_id.hex()[:8], reason)
        # If the async spawn hasn't landed yet, finish_spawn honors this
        # flag and terminates immediately — otherwise the orphan process
        # (and its lease/resources) would leak.
        w.kill_requested = True
        w.terminate()

    # ------------------------------------------------------------- worker pool
    @staticmethod
    def _pkg_pythonpath() -> str:
        """PYTHONPATH that puts this ray_tpu checkout first."""
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        existing = os.environ.get("PYTHONPATH")
        return pkg_root + (":" + existing if existing else "")

    def _worker_env(self, worker_id: WorkerID, tpu: bool) -> dict:
        """Per-worker environment variables (on top of the raylet's)."""
        env = {
            "PYTHONPATH": self._pkg_pythonpath(),
            "RAY_TPU_WORKER_ID": worker_id.hex(),
            "RAY_TPU_RAYLET_ADDRESS": self.address,
            "RAY_TPU_GCS_ADDRESS": self.gcs_address,
            "RAY_TPU_NODE_ID": self.node_id.hex(),
            "RAY_TPU_STORE_PATH": self.store_path,
            "RAY_TPU_SESSION_DIR": self.session_dir,
        }
        # Restore the TPU plugin hook ONLY for workers leased to
        # TPU-requesting work: the plugin's sitecustomize imports jax at
        # interpreter start (~2s) — paying that for every plain CPU
        # worker serializes large actor/task storms.
        pool_ips = os.environ.get("RAY_TPU_AXON_POOL_IPS")
        if tpu and pool_ips and self.resources_total.get("TPU", 0) > 0:
            env["PALLAS_AXON_POOL_IPS"] = pool_ips
        return env

    def _ensure_forkserver(self) -> subprocess.Popen:
        """Start (or restart) the warm template process. Caller holds
        _fork_lock. Runs on an executor thread, never the loop."""
        fs = self._forkserver
        if fs is not None and fs.poll() is None:
            return fs
        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # template must not load jax
        env["PYTHONPATH"] = self._pkg_pythonpath()
        log_path = os.path.join(self.session_dir, "logs", "forkserver.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        logf = open(log_path, "ab")
        fs = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.forkserver"],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=logf, start_new_session=True)
        logf.close()
        self._forkserver = fs
        return fs

    def _fork_worker(self, extra_env: dict, log_path: str) -> int:
        """Ask the template to fork a worker; returns the child pid.
        Caller is on an executor thread (blocking pipe I/O). Reads are
        select-bounded: a wedged template must fail THIS spawn (and get
        replaced) rather than deadlock every future spawn on the lock."""
        import select

        import msgpack

        header = struct.Struct("<I")

        def read_bounded(n: int) -> bytes:
            out = b""
            deadline = time.monotonic() + 20.0
            fd = fs.stdout.fileno()
            while len(out) < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not select.select(
                        [fd], [], [], remaining)[0]:
                    fs.kill()  # wedged: replace on next _ensure
                    raise RuntimeError("forkserver timed out; killed")
                chunk = os.read(fd, n - len(out))
                if not chunk:
                    raise RuntimeError("forkserver died mid-request")
                out += chunk
            return out

        with self._fork_lock:
            fs = self._ensure_forkserver()
            req = msgpack.packb({"env": extra_env, "log_path": log_path},
                                use_bin_type=True)
            fs.stdin.write(header.pack(len(req)) + req)
            fs.stdin.flush()
            (length,) = header.unpack(read_bounded(header.size))
            reply = msgpack.unpackb(read_bounded(length), raw=False)
        if "pid" not in reply:
            raise RuntimeError(f"forkserver spawn failed: {reply}")
        return reply["pid"]

    @staticmethod
    def _resolve_conda_python(conda: str) -> str:
        """Resolve a runtime_env['conda'] name/prefix to its interpreter.

        Conda semantics are interpreter-swap semantics (the reference
        wraps the worker command in `conda run`, runtime_env/conda.py):
        the named env's python runs the worker, so its site-packages ARE
        the environment — no sys.path games. This deployment is hermetic,
        so envs must be PRE-BUILT: a name resolves under
        $RAY_TPU_CONDA_ROOT/envs/<name>, a path containing '/' is used as
        the env prefix directly. The env needs msgpack installed (worker
        wire protocol); ray_tpu itself ships via PYTHONPATH."""
        if os.sep in conda:
            prefix = os.path.abspath(os.path.expanduser(conda))
        else:
            root = os.environ.get("RAY_TPU_CONDA_ROOT", "")
            if not root:
                raise RuntimeError(
                    f"runtime_env conda={conda!r} requires "
                    "RAY_TPU_CONDA_ROOT to point at a conda installation "
                    "with pre-built envs (hermetic deployment: envs are "
                    "not solved/created on the fly)")
            prefix = os.path.join(root, "envs", conda)
        py = os.path.join(prefix, "bin", "python")
        if not os.path.isfile(py):
            raise RuntimeError(
                f"conda env {conda!r} has no interpreter at {py}; "
                "build the env ahead of time (it must include msgpack)")
        return py

    def _spawn_worker(self, tpu: bool = False,
                      image_uri: str = "",
                      conda: str = "") -> WorkerHandle:
        worker_id = WorkerID.from_random()
        extra_env = self._worker_env(worker_id, tpu)
        log_path = os.path.join(self.session_dir, "logs",
                                f"worker-{worker_id.hex()[:12]}.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        # Pre-spawn validation FIRST (a raise here must not leave a ghost
        # WorkerHandle in self.workers):
        # Container hook (reference: runtime_env/image_uri.py): when the
        # env pins an image, the worker launches through the operator's
        # hook command — `<hook> <image_uri> <python> -m ...worker_main`
        # (e.g. a docker-run wrapper). Recorded here in the launch path;
        # no hook configured is a hard error surfaced to the creator.
        container_argv: Optional[List[str]] = None
        if image_uri:
            hook = os.environ.get("RAY_TPU_CONTAINER_HOOK", "")
            if not hook:
                raise RuntimeError(
                    f"runtime_env image_uri={image_uri!r} requires a "
                    "container hook (set RAY_TPU_CONTAINER_HOOK to a "
                    "wrapper command, e.g. a docker-run script)")
            import shlex as _shlex

            container_argv = _shlex.split(hook) + [image_uri]
        # Conda env = different interpreter (resolved before any process
        # starts so a bad env fails the lease, not the worker log).
        py_exe = self._resolve_conda_python(conda) if conda \
            else sys.executable
        w = WorkerHandle(worker_id, None, None)
        w.tpu = tpu
        w.log_path = log_path
        self.workers[worker_id] = w
        # TPU workers need the jax plugin imported at interpreter start
        # (sitecustomize), which a fork from the plugin-free template
        # can't provide — they keep the fresh-interpreter path. Container
        # and conda workers always launch their own interpreter.
        use_fork = self.config.forkserver_enabled and not image_uri and \
            not conda and \
            not (tpu and os.environ.get("RAY_TPU_AXON_POOL_IPS") and
                 self.resources_total.get("TPU", 0) > 0)

        # All spawn work OFF the io loop: a spawn storm (hundreds of
        # actors created at once) must not stall heartbeats — a blocked
        # loop gets the whole node declared dead by the GCS health
        # checker.
        def popen():
            env = dict(os.environ)
            env.update(extra_env)
            argv = (container_argv or []) + [
                py_exe, "-m", "ray_tpu._private.worker_main"]
            with open(log_path, "ab") as logf:
                return subprocess.Popen(
                    argv,
                    env=env, stdout=logf, stderr=subprocess.STDOUT,
                    start_new_session=True)

        async def finish_spawn():
            loop = asyncio.get_running_loop()
            pid = proc = None
            if use_fork:
                try:
                    pid = await loop.run_in_executor(
                        None, self._fork_worker, extra_env, log_path)
                except Exception:
                    logger.exception(
                        "forkserver spawn failed; falling back to popen")
            if pid is None:
                try:
                    proc = await loop.run_in_executor(None, popen)
                except Exception:
                    logger.exception("worker spawn failed")
                    # Full death path: releases the lease/resources this
                    # worker may already hold (actor leases are taken
                    # before spawn) and reports actor death to the GCS.
                    await self._on_worker_death(w)
                    return
            w.proc = proc
            w.pid = pid if pid is not None else proc.pid
            w.forked = proc is None
            if (self.dead or w.kill_requested) and w.alive():
                w.terminate()  # shut down / killed mid-spawn

        task = asyncio.get_event_loop().create_task(finish_spawn())
        self._spawn_tasks.add(task)
        task.add_done_callback(self._spawn_tasks.discard)
        return w

    async def _log_monitor_loop(self) -> None:
        """Tail every worker's log file and forward new lines to the GCS
        "logs" pubsub channel, where subscribed drivers print them
        (reference: python/ray/_private/log_monitor.py:103 — the driver
        sees every worker's stdout/stderr)."""
        while not self.dead:
            await asyncio.sleep(0.25)
            loop = asyncio.get_event_loop()
            for w in list(self.workers.values()):
                if w.log_path is None:
                    continue

                def read_chunk(path=w.log_path, off=w.log_offset):
                    with open(path, "rb") as f:
                        f.seek(off)
                        return f.read(256 * 1024)

                try:
                    # Off-loop: tailing hundreds of worker logs must not
                    # add blocking file I/O to the raylet's event loop.
                    chunk = await loop.run_in_executor(None, read_chunk)
                except OSError:
                    continue
                if not chunk:
                    continue
                w.log_offset += len(chunk)
                data = w.log_partial + chunk
                lines = data.split(b"\n")
                w.log_partial = lines.pop()  # tail w/o newline
                text_lines = [ln.decode("utf-8", "replace")
                              for ln in lines if ln.strip()]
                if not text_lines or self.gcs is None or self.gcs.closed:
                    continue
                try:
                    await self.gcs.notify("publish_logs", {
                        "lines": text_lines,
                        "pid": w.pid,
                        "worker_id": w.worker_id.binary(),
                        # Lets each driver filter to its own job's
                        # workers (None while the worker is unleased).
                        "job_id": w.job_id,
                        "node": self.address,
                    })
                except Exception:
                    pass

    async def handle_register_worker(self, data, conn) -> dict:
        worker_id = WorkerID(data["worker_id"])
        w = self.workers.get(worker_id)
        if w is None:
            # Driver registration: not a pool worker.
            w = WorkerHandle(worker_id, data.get("pid", 0))
            w.state = "driver"
            self.workers[worker_id] = w
        w.address = data["address"]
        w.fast_address = data.get("fast_address", "")
        w.conn = conn
        conn.on_close = lambda c, w=w: self._on_conn_close(w)
        w.registered.set()
        if w.state == "starting":
            w.state = "idle"
            self.idle_workers.append(w)
            self._drain_queue()
        return {"node_id": self.node_id.binary(), "ok": True}

    def _on_conn_close(self, w: WorkerHandle) -> None:
        if w.state == "driver":
            self.workers.pop(w.worker_id, None)
            return
        # Registered workers die with their raylet connection (the worker
        # side exits on conn loss; the reverse direction is detected
        # here). This is the pid-independent death signal for forked
        # workers — the _reap_loop's os.kill(pid, 0) probe alone has a
        # one-tick PID-reuse window (forkserver children are auto-reaped).
        if not self.dead and w.state != "dead" and w.registered.is_set():
            asyncio.get_event_loop().create_task(self._on_worker_death(w))

    def _pool_capacity(self) -> int:
        soft = self.config.num_workers_soft_limit
        if soft <= 0:
            soft = max(int(self.resources_total.get("CPU", 1)), 1)
        return soft

    # ------------------------------------------------------------- leases
    async def handle_get_cluster_view(self, data, conn) -> list:
        """Debug/testing: this raylet's current gossip view (what its
        spillback decisions are based on)."""
        return self.cluster_view

    async def handle_list_store_objects(self, data, conn) -> list:
        """This node's shm store contents (id, size, pin count) — one
        shard of the cluster-wide `list objects` state query (reference:
        the per-core-worker object tables behind `ray list objects`)."""
        import ctypes

        from ray_tpu.core import shm_client as sc

        lib = sc._load()
        max_n = int(data.get("limit", 4096))
        ids_buf = (ctypes.c_uint8 * (24 * max_n))()
        sizes = (ctypes.c_uint64 * max_n)()
        refs = (ctypes.c_int64 * max_n)()
        n = lib.shm_list(self.store._ptr, ids_buf, sizes, refs, max_n)
        return [{"object_id": bytes(ids_buf[i * 24:(i + 1) * 24]).hex(),
                 "size_bytes": int(sizes[i]),
                 "pins": int(refs[i]),
                 "node_id": self.node_id.hex()}
                for i in range(n)]

    async def handle_request_worker_lease(self, data, conn) -> dict:
        req = LeaseRequest(data)
        if os.environ.get("RAY_TPU_TRACE_LEASES"):
            logger.info(
                "LEASE req=%s res=%s spills=%d avail=%s queue=%d view=%s",
                req.lease_id.hex()[:6], req.resources, req.num_spillbacks,
                self.available, len(self.lease_queue),
                [(n["node_id"].hex()[:6], n["resources_available"])
                 for n in self.cluster_view])
        if not self._feasible_ever(req):
            target = self._find_spillback_target(req, require_available=False)
            if target:
                return {"spillback": target}
            # No capable node *yet*: queue — reference semantics are that
            # infeasible tasks stay pending until resources appear.
        # Hybrid spillback: local under pressure, someone else has room
        # now. "Pressure" counts requests already QUEUED ahead of this
        # one (reference: ClusterTaskManager accounts allocated AND
        # queued demand) — without that, a burst arriving before the
        # first grant deducts resources sees stale availability and
        # serializes locally instead of spreading.
        if not self._can_grant_now(req, include_queued=True) and \
                req.num_spillbacks < 3:
            target = self._find_spillback_target(req, require_available=True)
            if target and target != self.address:
                if os.environ.get("RAY_TPU_TRACE_LEASES"):
                    logger.info("LEASE req=%s SPILL -> %s",
                                req.lease_id.hex()[:6], target)
                return {"spillback": target}
        if os.environ.get("RAY_TPU_TRACE_LEASES"):
            logger.info("LEASE req=%s QUEUE locally",
                        req.lease_id.hex()[:6])
        self.lease_queue.append(req)
        self._drain_queue()
        granted = await req.grant_fut
        return granted

    async def handle_cancel_lease_request(self, data, conn) -> bool:
        lease_id = data["lease_id"]
        for req in list(self.lease_queue):
            if req.lease_id == lease_id:
                self.lease_queue.remove(req)
                if not req.grant_fut.done():
                    req.grant_fut.set_result({"error": "canceled"})
                return True
        return False

    def _bundle_pool(self, req: LeaseRequest) -> Optional[dict]:
        if req.pg_id is None:
            return None
        return self.bundles.get((req.pg_id, max(req.pg_bundle, 0)))

    def _feasible_ever(self, req: LeaseRequest) -> bool:
        if req.pg_id is not None:
            pool = self._bundle_pool(req)
            return pool is not None and pool["committed"] and \
                _fits(req.resources, pool["reserved"])
        return _fits(req.resources, self.resources_total)

    def _can_grant_now(self, req: LeaseRequest,
                       include_queued: bool = False) -> bool:
        pool = self._bundle_pool(req)
        if req.pg_id is not None:
            return pool is not None and pool["committed"] and \
                _fits(req.resources, pool["available"])
        avail = self.available
        if include_queued:
            queued = {}
            for r in self.lease_queue:
                if r is not req and not r.grant_fut.done() and \
                        r.pg_id is None:
                    for k, v in r.resources.items():
                        queued[k] = queued.get(k, 0) + v
            if queued:
                avail = {k: v - queued.get(k, 0)
                         for k, v in avail.items()}
        return _fits(req.resources, avail)

    def _debited_available(self, n: dict) -> dict:
        """Node availability minus this raylet's recent spillback debits.

        Spilling deducts optimistically so back-to-back decisions fan
        out — but a cluster_view broadcast REPLACES the cached view,
        and one captured before the spilled request landed at its
        target resurrects the stale availability (observed: 3 held
        tasks landing on 2 nodes). Debits live in an overlay with a
        short TTL (long enough for the target's own grant to reach the
        next broadcast) so they survive view refreshes."""
        now = time.monotonic()
        self._spill_debits = [(exp, nid, res) for exp, nid, res in
                              getattr(self, "_spill_debits", [])
                              if exp > now]
        avail = dict(n["resources_available"])
        for _exp, nid, res in self._spill_debits:
            if nid == n["node_id"]:
                for k, v in res.items():
                    avail[k] = avail.get(k, 0) - v
        return avail

    def _find_spillback_target(self, req: LeaseRequest,
                               require_available: bool) -> Optional[str]:
        if req.pg_id is not None:
            return None  # PG tasks are pinned to their bundle's node
        best = None
        for n in self.cluster_view:
            if n["node_id"] == self.node_id.binary():
                continue
            avail = self._debited_available(n)
            pool = avail if require_available else n["resources_total"]
            if _fits(req.resources, pool):
                score = sum(avail.values())
                if best is None or score > best[0]:
                    best = (score, n)
        if best is None:
            return None
        if require_available:
            self._spill_debits.append(
                (time.monotonic() + 2.0, best[1]["node_id"],
                 dict(req.resources)))
        return best[1]["address"]

    def _drain_queue(self) -> None:
        made_progress = True
        while made_progress and self.lease_queue:
            made_progress = False
            for req in list(self.lease_queue):
                if req.grant_fut.done():
                    self.lease_queue.remove(req)
                    continue
                if not self._can_grant_now(req):
                    continue
                needs_tpu = req.resources.get("TPU", 0) > 0
                worker = self._take_idle_worker(tpu=needs_tpu)
                if worker is None:
                    n_starting = sum(1 for w in self.workers.values()
                                     if w.state == "starting")
                    n_live = sum(1 for w in self.workers.values()
                                 if w.state in ("starting", "idle", "leased"))
                    if n_live < self._pool_capacity() or n_starting == 0:
                        self._spawn_worker(tpu=needs_tpu)
                    break  # wait for registration
                self.lease_queue.remove(req)
                self._grant(req, worker)
                made_progress = True
        # Re-evaluate spillback for starved requests: resources freed up on
        # another node since this request was queued (reference:
        # ClusterTaskManager::ScheduleAndDispatchTasks runs the cluster-wide
        # policy on every state change).
        for req in list(self.lease_queue):
            if req.grant_fut.done() or self._can_grant_now(req):
                continue
            # Locally-infeasible requests may always spill; feasible-but-busy
            # ones only a few times (to bound ping-pong).
            if self._feasible_ever(req) and req.num_spillbacks >= 3:
                continue
            target = self._find_spillback_target(req, require_available=True)
            if target and target != self.address:
                self.lease_queue.remove(req)
                req.grant_fut.set_result({"spillback": target})

    def _maybe_refill_pool(self) -> None:
        """Keep a standing pool of registered idle workers (reference:
        WorkerPool::PrestartWorkers): actor storms and task bursts then
        consume warm workers instead of paying process bring-up inline.
        Actor-bound workers leave the pool permanently, so the refill is
        what keeps storms fast beyond the first wave."""
        if not self.config.prestart_workers or self.dead:
            return
        min_idle = self._pool_capacity()
        n_idle = sum(1 for w in self.idle_workers if w.state == "idle")
        n_starting = sum(1 for w in self.workers.values()
                         if w.state == "starting")
        for _ in range(max(0, min_idle - n_idle - n_starting)):
            self._spawn_worker()

    def _schedule_pool_refill(self, delay: float = 0.25) -> None:
        """Refill after a consumed pool worker — debounced ONLY while a
        storm is in flight: replacement spawns must not compete with the
        storm's own worker bring-ups for CPU (a 16-actor storm otherwise
        pays 32 process starts up front), but steady sub-`delay` actor
        creation must not starve the refill either (each consumption
        re-arming the timer would drain the pool and force cold inline
        spawns). Heuristic: spawns already in flight = storm = debounce;
        quiet pool = refill immediately."""
        n_starting = sum(1 for w in self.workers.values()
                         if w.state == "starting")
        if n_starting == 0:
            self._maybe_refill_pool()
            return
        handle = getattr(self, "_refill_handle", None)
        if handle is not None:
            handle.cancel()
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._maybe_refill_pool()
            return
        self._refill_handle = loop.call_later(
            delay, self._maybe_refill_pool)

    def _take_idle_worker(self, tpu: bool = False
                          ) -> Optional[WorkerHandle]:
        keep: List[WorkerHandle] = []
        found = fallback = None
        while self.idle_workers:
            w = self.idle_workers.pop()
            if w.state != "idle" or not w.alive():
                continue  # dead/stale entry
            if w.tpu == tpu:
                found = w
                break
            if not tpu and w.tpu and fallback is None:
                # CPU work runs fine on a TPU-flavored worker (its env
                # is a superset); reuse beats spawning — and prevents
                # unbounded pool growth under mixed workloads.
                fallback = w
                continue
            keep.append(w)
        self.idle_workers.extend(keep)
        if found is None and fallback is not None:
            return fallback
        if found is not None and fallback is not None:
            self.idle_workers.append(fallback)
        return found

    def _grant(self, req: LeaseRequest, worker: WorkerHandle) -> None:
        bundle_key = None
        if req.pg_id is not None:
            bundle_key = (req.pg_id, max(req.pg_bundle, 0))
            pool = self.bundles[bundle_key]
            for k, v in req.resources.items():
                pool["available"][k] = pool["available"].get(k, 0) - v
        else:
            for k, v in req.resources.items():
                self.available[k] = self.available.get(k, 0) - v
        worker.state = "leased"
        worker.lease_id = req.lease_id
        worker.job_id = req.job_id
        worker.lease_started = time.monotonic()
        self.leases[req.lease_id] = (worker, dict(req.resources), bundle_key)
        self._notify_resources_changed()
        req.grant_fut.set_result({
            "granted": True,
            "worker_address": worker.address,
            "worker_fast_address": worker.fast_address,
            "worker_id": worker.worker_id.binary(),
        })

    def _release_resources(self, res: Dict[str, float],
                           bundle_key) -> None:
        if bundle_key is not None:
            pool = self.bundles.get(bundle_key)
            if pool:
                for k, v in res.items():
                    pool["available"][k] = pool["available"].get(k, 0) + v
        else:
            for k, v in res.items():
                self.available[k] = self.available.get(k, 0) + v
        self._notify_resources_changed()

    async def handle_return_worker(self, data, conn) -> bool:
        lease_id = data["lease_id"]
        entry = self.leases.pop(lease_id, None)
        if entry is None:
            return False
        worker, res, bundle_key = entry
        self._release_resources(res, bundle_key)
        if data.get("disconnect") or worker.state == "dead":
            if worker.proc or worker.forked:
                await self._kill_worker(worker, "returned with disconnect")
        elif worker.state == "leased":
            worker.state = "idle"
            worker.lease_id = None
            self.idle_workers.append(worker)
        self._drain_queue()
        return True

    # ------------------------------------------------------- actor leases
    async def handle_lease_worker_for_actor(self, data, conn) -> dict:
        """GCS asks this node to host an actor: spawn a dedicated worker and
        push the creation task to it (reference: raylet grants a worker
        lease for the actor-creation task; worker stays bound for life)."""
        from ray_tpu.core.task_spec import TaskSpec

        spec = TaskSpec.from_wire(data["task"])
        if not _fits(spec.resources, self.available) and \
                spec.placement_group_id is None:
            return {"ok": False, "error": "insufficient resources"}
        bundle_key = None
        if spec.placement_group_id is not None:
            bundle_key = (spec.placement_group_id.binary(),
                          max(spec.placement_group_bundle_index, 0))
            pool = self.bundles.get(bundle_key)
            if pool is None or not pool["committed"] or \
                    not _fits(spec.resources, pool["available"]):
                return {"ok": False, "error": "bundle unavailable"}
            for k, v in spec.resources.items():
                pool["available"][k] = pool["available"].get(k, 0) - v
        else:
            for k, v in spec.resources.items():
                self.available[k] = self.available.get(k, 0) - v
        # Idle-worker reuse (reference: WorkerPool hands pooled workers to
        # actor leases): an already-registered pool worker skips process
        # startup entirely — the dominant cost of actor-creation storms.
        needs_tpu = spec.resources.get("TPU", 0) > 0
        self._notify_resources_changed()
        renv = spec.runtime_env or {}
        image_uri = renv.get("image_uri", "")
        conda_env = renv.get("conda", "")
        if isinstance(conda_env, dict):
            # Spec-form conda ({"dependencies": [...]}) needs a solver —
            # not available hermetically. Named pre-built envs only.
            # permanent: the GCS must fail the actor with THIS error, not
            # retry into a generic "no feasible node".
            self._release_resources(dict(spec.resources),
                                    bundle_key)
            return {"ok": False, "permanent": True, "error":
                    "runtime_env conda specs (dependency lists) are not "
                    "supported in this hermetic deployment; pre-build the "
                    "env and pass its NAME (under RAY_TPU_CONDA_ROOT) or "
                    "prefix path"}
        dedicated = bool(image_uri or conda_env)
        w = None if dedicated else self._take_idle_worker(tpu=needs_tpu)
        if w is None:
            try:
                w = self._spawn_worker(tpu=needs_tpu, image_uri=image_uri,
                                       conda=conda_env)
            except RuntimeError as e:  # pre-spawn validation: image_uri
                # without a hook, unresolvable conda env — permanent
                # config errors; retrying other nodes gives the same
                # answer, so the GCS should surface THIS message.
                if spec.placement_group_id is None:
                    self._release_resources(dict(spec.resources), None)
                else:
                    self._release_resources(dict(spec.resources),
                                            bundle_key)
                return {"ok": False, "permanent": True, "error": str(e)}
        else:
            # Replace the consumed pool worker once the storm quiets
            # (debounced — replacements off the storm's critical path).
            self._schedule_pool_refill()
        w.state = "actor"
        w.actor_id = data["actor_id"]
        w.job_id = spec.job_id.binary()
        lease_id = os.urandom(16)
        w.lease_id = lease_id
        self.leases[lease_id] = (w, dict(spec.resources), bundle_key)
        trace = os.environ.get("RAY_TPU_TRACE_STARTUP")
        t0 = time.monotonic()

        def tr(msg):
            if trace:
                logger.info("TRACE lease %s +%.3f %s",
                            w.worker_id.hex()[:6], time.monotonic() - t0,
                            msg)

        tr("spawned, waiting registration")
        try:
            await asyncio.wait_for(w.registered.wait(),
                                   self.config.worker_startup_timeout_s)
            tr("registered, pushing creation")
            await w.conn.call("push_task", {"task": data["task"]},
                              timeout=self.config.worker_startup_timeout_s)
            tr("creation pushed + done")
        except Exception as e:
            await self._kill_worker(w, f"actor creation failed: {e}")
            return {"ok": False, "error": str(e)}
        return {"ok": True, "worker_address": w.address}

    # ------------------------------------------------------- placement bundles
    async def handle_prepare_bundle(self, data, conn) -> dict:
        key = (data["pg_id"], data["bundle_index"])
        res = data["resources"]
        if key in self.bundles:
            return {"ok": True}
        if not _fits(res, self.available):
            return {"ok": False, "error": "insufficient resources"}
        for k, v in res.items():
            self.available[k] = self.available.get(k, 0) - v
        self.bundles[key] = {"reserved": dict(res), "available": dict(res),
                             "committed": False}
        return {"ok": True}

    async def handle_commit_bundle(self, data, conn) -> bool:
        key = (data["pg_id"], data["bundle_index"])
        if key in self.bundles:
            self.bundles[key]["committed"] = True
            self._drain_queue()
        return True

    async def handle_cancel_bundle(self, data, conn) -> bool:
        key = (data["pg_id"], data["bundle_index"])
        pool = self.bundles.pop(key, None)
        if pool:
            for k, v in pool["reserved"].items():
                self.available[k] = self.available.get(k, 0) + v
            self._drain_queue()
        return True

    # ------------------------------------------------------- object manager
    async def handle_pull_object(self, data, conn) -> dict:
        """Ensure the object is in the local store (fetch/restore), or report
        where it actually is ('inline' = ask the owner's memory store)."""
        oid = ObjectID(data["object_id"])
        key = oid.binary()
        if self.store.contains(oid):
            return {"status": "local"}
        fut = self._pulls_inflight.get(key)
        if fut is None:
            fut = asyncio.get_event_loop().create_task(
                self._pull(oid, data.get("owner_address")))
            self._pulls_inflight[key] = fut
        try:
            return await asyncio.wait_for(
                asyncio.shield(fut), data.get("timeout", 30.0))
        except asyncio.TimeoutError:
            return {"status": "timeout"}
        finally:
            if fut.done():
                self._pulls_inflight.pop(key, None)

    async def _pull(self, oid: ObjectID, owner_address: Optional[str]) -> dict:
        deadline = time.monotonic() + 30.0
        key = oid.binary()
        while time.monotonic() < deadline:
            if self.store.contains(oid):
                return {"status": "local"}
            if key in self._spilled_local:
                ok = await self._restore_spilled(oid,
                                                 self._spilled_local[key])
                if ok:
                    return {"status": "local"}
            locs = await self.gcs.call("get_object_locations",
                                       {"object_id": key})
            for node in locs.get("nodes", []):
                if node["node_id"] == self.node_id.binary():
                    continue
                ok = await self._fetch_from_remote(
                    oid, node["address"], node.get("transfer_port", 0))
                if ok:
                    await self.gcs.call("add_object_location", {
                        "object_id": key,
                        "node_id": self.node_id.binary()})
                    return {"status": "local"}
            url = locs.get("spilled_url")
            if url:
                ok = await self._restore_spilled(oid, url)
                if ok:
                    return {"status": "local"}
            await asyncio.sleep(0.05)
        return {"status": "not_found"}

    async def _fetch_from_remote(self, oid: ObjectID, address: str,
                                 transfer_port: int = 0) -> bool:
        # Fast path: native store-to-store streaming (transfer.cpp) — no
        # Python on the data plane. Falls back to rpc chunks if the remote
        # has no transfer server or the native pull fails.
        # The fetch client opens the local store itself — the remote's
        # transfer_port is all that matters.
        if transfer_port:
            host = address.rsplit(":", 1)[0]
            try:
                from ray_tpu.core import transfer_client as tc

                rc = await asyncio.get_event_loop().run_in_executor(
                    None, tc.fetch, self.store_path, host, transfer_port,
                    oid.binary())
                if rc in (tc.FETCH_OK, tc.FETCH_ALREADY_LOCAL):
                    return True
            except Exception as e:
                logger.info("native fetch of %s from %s:%d failed (%s); "
                            "falling back to rpc", oid.hex()[:8], host,
                            transfer_port, e)
        try:
            host, port = address.rsplit(":", 1)
            c = await rpc.connect(host, int(port), timeout=5.0,
                                  name="om-fetch")
        except Exception:
            return False
        try:
            meta = await c.call("om_object_info", {"object_id": oid.binary()},
                                timeout=10.0)
            if not meta.get("found"):
                return False
            size = meta["size"]
            # Write straight into the local store allocation, chunk by chunk.
            import ctypes

            from ray_tpu.core import shm_client as sc

            off = ctypes.c_uint64()
            rcode = sc._load().shm_create(self.store._ptr, oid.binary(), size,
                                          ctypes.byref(off))
            if rcode == sc.ERR_EXISTS:
                return True
            if rcode != sc.OK:
                return False
            try:
                pos = 0
                while pos < size:
                    n = min(CHUNK, size - pos)
                    chunk = await c.call("om_fetch", {
                        "object_id": oid.binary(), "offset": pos,
                        "length": n}, timeout=30.0)
                    if chunk is None:
                        raise IOError("remote object vanished mid-transfer")
                    self.store._mv[off.value + pos: off.value + pos + len(chunk)] = chunk
                    pos += len(chunk)
            except BaseException:
                sc._load().shm_abort(self.store._ptr, oid.binary())
                raise
            sc._load().shm_seal(self.store._ptr, oid.binary())
            sc._load().shm_release(self.store._ptr, oid.binary())
            return True
        except Exception as e:
            logger.info("fetch of %s from %s failed: %s",
                        oid.hex()[:8], address, e)
            return False
        finally:
            await c.close()

    async def handle_om_object_info(self, data, conn) -> dict:
        oid = ObjectID(data["object_id"])
        buf = self.store.get(oid, timeout_ms=0)
        if buf is None:
            return {"found": False}
        size = len(buf.data)
        buf.release()
        return {"found": True, "size": size}

    async def handle_om_fetch(self, data, conn):
        oid = ObjectID(data["object_id"])
        buf = self.store.get(oid, timeout_ms=0)
        if buf is None:
            return None
        try:
            off, length = data["offset"], data["length"]
            return bytes(buf.data[off: off + length])
        finally:
            buf.release()

    async def handle_free_object(self, data, conn) -> bool:
        """Owner-driven deletion (distributed refcount hit zero)."""
        oid = ObjectID(data["object_id"])
        self.store.delete(oid)
        try:
            await self.gcs.call("remove_object_location", {
                "object_id": oid.binary(),
                "node_id": self.node_id.binary()})
        except Exception:
            pass
        return True

    # ------------------------------------------------------- spilling
    def _spill_storage(self):
        """Spill backend per config (reference:
        python/ray/_private/external_storage.py:72 — filesystem, or any
        URI-schemed backend: fsspec / registered plugin)."""
        if self._spill_backend is None:
            from ray_tpu._private.external_storage import storage_for_path

            path = self.config.object_spilling_dir or \
                os.path.join(self.session_dir, "spill")
            self._spill_backend = storage_for_path(path)
        return self._spill_backend

    async def _spill_loop(self) -> None:
        while not self.dead:
            await asyncio.sleep(0.5)
            try:
                stats = self.store.stats()
                if stats["capacity"] == 0 or \
                        stats["bytes_used"] / stats["capacity"] < \
                        self.config.object_spilling_threshold:
                    continue
                await self._spill_once()
            except Exception:
                logger.exception("spill loop error")

    async def _spill_once(self) -> None:
        """Spill one unreferenced sealed object to external storage
        (reference: LocalObjectManager::SpillObjects)."""
        import ctypes

        from ray_tpu.core import shm_client as sc

        lib = sc._load()
        max_n = 256
        ids_buf = (ctypes.c_uint8 * (24 * max_n))()
        sizes = (ctypes.c_uint64 * max_n)()
        refs = (ctypes.c_int64 * max_n)()
        n = lib.shm_list(self.store._ptr, ids_buf, sizes, refs, max_n)
        best = None
        for i in range(n):
            if refs[i] == 0:
                if best is None or sizes[i] > sizes[best]:
                    best = i
        if best is None:
            return
        oid = ObjectID(bytes(ids_buf[best * 24:(best + 1) * 24]))
        buf = self.store.get(oid, timeout_ms=0)
        if buf is None:
            return
        storage = self._spill_storage()
        loop = asyncio.get_event_loop()
        # The pinned shm view streams straight to storage (no heap copy —
        # the node is under memory pressure right now); remote backends
        # block on IO, so write off-loop. Release the pin after.
        try:
            url = await loop.run_in_executor(None, storage.put, oid.hex(),
                                             buf.data)
        finally:
            buf.release()
        self.store.delete(oid)
        self._spilled_local[oid.binary()] = url
        await self.gcs.call("add_spilled_object",
                            {"object_id": oid.binary(), "url": url})
        await self.gcs.call("remove_object_location", {
            "object_id": oid.binary(), "node_id": self.node_id.binary()})
        logger.info("spilled %s (%d bytes) to %s", oid.hex()[:8],
                    sizes[best], url)

    async def _restore_spilled(self, oid: ObjectID, url: str) -> bool:
        from ray_tpu._private.external_storage import storage_for_path

        try:
            # Restore via the url's own backend (the object may have been
            # spilled by a different node with a different local config).
            storage = storage_for_path(url)
            loop = asyncio.get_event_loop()
            data = await loop.run_in_executor(None, storage.get, url)
        except Exception:
            return False
        try:
            self.store.put_bytes(oid, data)
        except StoreFullError:
            return False
        self._spilled_local.pop(oid.binary(), None)
        await self.gcs.call("add_object_location", {
            "object_id": oid.binary(), "node_id": self.node_id.binary()})
        return True

    # ------------------------------------------------------- stats
    async def handle_node_stats(self, data, conn) -> dict:
        return {
            "node_id": self.node_id.binary(),
            "resources_total": self.resources_total,
            "resources_available": self.available,
            "num_workers": len(self.workers),
            "num_idle": len(self.idle_workers),
            "lease_queue": len(self.lease_queue),
            "store": self.store.stats(),
            "bundles": {f"{k[0].hex()[:8]}:{k[1]}": v["committed"]
                        for k, v in self.bundles.items()},
        }

    async def handle_ping(self, data, conn) -> str:
        return "pong"


def _fits(demand: Dict[str, float], available: Dict[str, float]) -> bool:
    return all(available.get(k, 0.0) >= v for k, v in demand.items() if v > 0)


def main():  # pragma: no cover - exercised via subprocess in tests
    import argparse
    import json

    p = argparse.ArgumentParser()
    p.add_argument("--gcs-address", required=True)
    p.add_argument("--store-path", required=True)
    p.add_argument("--resources", required=True)  # JSON dict
    p.add_argument("--session-dir", required=True)
    p.add_argument("--node-id", default="")
    p.add_argument("--labels", default="{}")
    p.add_argument("--slice-id", default="")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--config", default="{}")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s raylet %(levelname)s %(message)s")

    async def run():
        cfg = Config.from_dict(json.loads(args.config)) if args.config != "{}" \
            else Config.from_env()
        node_id = NodeID.from_hex(args.node_id) if args.node_id \
            else NodeID.from_random()
        raylet = Raylet(node_id, args.gcs_address, args.store_path,
                        json.loads(args.resources), cfg, args.session_dir,
                        labels=json.loads(args.labels),
                        slice_id=args.slice_id)
        port = await raylet.start(args.host, args.port)
        print(json.dumps({"port": port, "node_id": node_id.hex()}),
              flush=True)
        await asyncio.Event().wait()

    from ray_tpu._private.profiling_hook import maybe_enable_profiler

    maybe_enable_profiler("raylet")
    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
