"""Forkserver (zygote) worker factory.

TPU-native equivalent of the reference's worker prestart/reuse machinery
(src/ray/raylet/worker_pool.h:359 ``PrestartWorkers``, :425
``StartWorkerProcess``): instead of paying the Python interpreter + import
cold start (~0.25 s solo, >1 s under spawn storms — round-3 root cause)
for every worker, the raylet keeps ONE warm template process with the
worker's import graph already loaded and asks it to ``fork()`` children:
~10 ms per worker, constant under storms.

Protocol (template stdin/stdout, length-prefixed msgpack):
  request : {"env": {str: str}, "log_path": str}
  reply   : {"pid": int}  |  {"error": str}

Design constraints honored here:
- The template stays SINGLE-THREADED and never starts an event loop, so
  fork() is safe (threads don't survive fork; the child starts its own
  asyncio loop inside worker_main).
- The template must NOT import jax: TPU-flavored workers need the jax
  plugin imported at interpreter start (sitecustomize), so the raylet
  keeps the plain-subprocess path for those.
- SIGCHLD is SIG_IGN so exited workers are auto-reaped (no zombies);
  the raylet checks liveness by pid.
"""

from __future__ import annotations

import os
import signal
import struct
import sys

_LEN = struct.Struct("<I")


def _read_exact(fd: int, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = os.read(fd, n - len(out))
        if not chunk:
            raise EOFError
        out += chunk
    return out


def _child_main(req: dict) -> None:
    """Runs in the forked child: become a clean worker process."""
    os.setsid()
    log_fd = os.open(req["log_path"],
                     os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    os.dup2(log_fd, 1)
    os.dup2(log_fd, 2)
    if log_fd > 2:
        os.close(log_fd)
    # Detach from the template's control pipe.
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)
    if devnull > 2:
        os.close(devnull)
    os.environ.update(req["env"])
    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
    from ray_tpu._private import worker_main

    worker_main.main()


def main() -> None:
    # Auto-reap forked workers; the raylet tracks liveness by pid.
    signal.signal(signal.SIGCHLD, signal.SIG_IGN)
    # Pre-import the worker's module graph ONCE; every fork inherits it.
    import msgpack

    from ray_tpu._private import worker_main  # noqa: F401  (warms imports)

    # Modules the worker only pulls in lazily AFTER fork (profiled in a
    # 16-actor storm: concurrent.futures.thread via the first
    # ThreadPoolExecutor, queue via it, fastlane inside connect()) —
    # import them here so forks inherit the bytecode. Also dlopen the
    # native libs: .so mappings survive fork, saving two dlopens per
    # worker. No threads are created (fork safety); fl_server_create is
    # NOT called here.
    import concurrent.futures.thread  # noqa: F401
    import queue  # noqa: F401

    # Actor creation imports runtime_env inside the handler; on a
    # 1-core box a 32-actor storm pays 32 serialized cold imports
    # (~20 ms each) without this warm-up.
    from ray_tpu._private import runtime_env  # noqa: F401
    from ray_tpu.core import fastlane, shm_client

    try:
        fastlane._load()
        shm_client._load()
    except Exception:
        pass  # workers fall back to loading on demand

    in_fd = 0
    out_fd = 1
    while True:
        try:
            (length,) = _LEN.unpack(_read_exact(in_fd, _LEN.size))
            req = msgpack.unpackb(_read_exact(in_fd, length), raw=False)
        except EOFError:
            return  # raylet closed the pipe: shut down
        try:
            pid = os.fork()
        except OSError as e:
            reply = msgpack.packb({"error": str(e)}, use_bin_type=True)
            os.write(out_fd, _LEN.pack(len(reply)) + reply)
            continue
        if pid == 0:
            code = 0
            try:
                _child_main(req)
            except BaseException:
                # Surface startup failures in the worker log (stderr is
                # the log file once dup2 ran; the template's log before).
                code = 1
                try:
                    import traceback

                    traceback.print_exc()
                    sys.stderr.flush()
                except Exception:
                    pass
            finally:
                os._exit(code)
        reply = msgpack.packb({"pid": pid}, use_bin_type=True)
        os.write(out_fd, _LEN.pack(len(reply)) + reply)


if __name__ == "__main__":
    main()
