"""@remote decorator — remote functions and actor classes.

Equivalent of the reference's remote_function.py:40 (RemoteFunction,
``_remote`` :266) and actor.py:566 (ActorClass): ``@remote`` wraps a
function into ``.remote()/.options()`` task submission or a class into an
actor factory.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Optional

from ray_tpu.core.actor import ActorClass

_OPTION_KEYS = {
    "num_cpus", "num_gpus", "num_tpus", "memory", "resources", "num_returns",
    "max_retries", "retry_exceptions", "max_restarts", "max_task_retries",
    "max_concurrency", "name", "namespace", "lifetime", "runtime_env",
    "scheduling_strategy", "placement_group", "placement_group_bundle_index",
    "label_selector",
}


def _check_opts(opts: dict) -> None:
    bad = set(opts) - _OPTION_KEYS
    if bad:
        raise ValueError(f"unknown @remote options: {sorted(bad)}")


class RemoteFunction:
    def __init__(self, fn: Callable, opts: dict):
        _check_opts(opts)
        self._function = fn
        self._opts = opts
        self._resolved_opts = None  # _resolve_strategy memo (opts are frozen)
        self._descriptor = None
        self._descriptor_session = None  # session token of the export
        self.__name__ = getattr(fn, "__name__", "remote_function")
        self.__doc__ = getattr(fn, "__doc__", None)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self.__name__}() cannot be called directly; "
            f"use {self.__name__}.remote()")

    def options(self, **opts) -> "RemoteFunction":
        merged = {**self._opts, **opts}
        new = RemoteFunction(self._function, merged)
        new._descriptor = self._descriptor
        new._descriptor_session = self._descriptor_session
        return new

    def remote(self, *args, **kwargs):
        from ray_tpu._private.worker import global_worker

        worker = global_worker()
        # Module-level remote functions outlive clusters: re-export when
        # the session changed (a fresh GCS has an empty function table).
        if self._descriptor is None or \
                self._descriptor_session != worker.core.worker_id.binary():
            self._descriptor = worker.export(self._function)
            self._descriptor_session = worker.core.worker_id.binary()
        opts = self._resolved_opts
        if opts is None:
            opts = self._resolved_opts = _resolve_strategy(self._opts)
        refs = worker.submit_task(self._descriptor, args, kwargs, opts)
        num_returns = opts.get("num_returns", 1)
        if num_returns == 1 or num_returns == "streaming":
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: python/ray/dag/dag_node.py .bind())."""
        from ray_tpu.dag.dag_node import FunctionNode

        return FunctionNode(self, args, kwargs)


def _resolve_strategy(opts: dict) -> dict:
    """Normalize scheduling_strategy / placement_group options to wire form."""
    from ray_tpu.core.placement_group import PlacementGroup
    from ray_tpu.core.scheduling_strategies import (
        NodeAffinitySchedulingStrategy, PlacementGroupSchedulingStrategy)

    opts = dict(opts)
    strategy = opts.get("scheduling_strategy")
    pg = opts.pop("placement_group", None)
    bundle = opts.pop("placement_group_bundle_index", -1)
    if isinstance(strategy, PlacementGroupSchedulingStrategy):
        opts["scheduling_strategy"] = {
            "type": "placement_group",
            "pg_id": strategy.placement_group.id.binary(),
            "bundle_index": strategy.placement_group_bundle_index,
        }
    elif isinstance(strategy, NodeAffinitySchedulingStrategy):
        opts["scheduling_strategy"] = {
            "type": "node_affinity",
            "node_id": strategy.node_id if isinstance(strategy.node_id, bytes)
            else bytes.fromhex(strategy.node_id),
            "soft": strategy.soft,
        }
    elif isinstance(strategy, str) and strategy == "SPREAD":
        opts["scheduling_strategy"] = {"type": "spread"}
    elif isinstance(pg, PlacementGroup):
        opts["scheduling_strategy"] = {
            "type": "placement_group",
            "pg_id": pg.id.binary(),
            "bundle_index": bundle,
        }
    return opts


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=...)`` decorator."""
    if len(args) == 1 and not kwargs and (inspect.isclass(args[0]) or
                                          callable(args[0])):
        return _wrap(args[0], {})
    if args:
        raise TypeError("remote() takes keyword options only")
    return lambda obj: _wrap(obj, kwargs)


def _wrap(obj, opts: dict):
    if inspect.isclass(obj):
        return ActorClass(obj, opts)
    return RemoteFunction(obj, opts)


def method(**opts):
    """Per-method options on actors (reference: python/ray/actor.py
    ``@ray.method(num_returns=...)``)."""

    def decorator(fn):
        fn.__ray_tpu_method_opts__ = opts
        return fn

    return decorator
