"""Accelerator (TPU) detection and slice topology.

Equivalent of the reference's TPUAcceleratorManager
(python/ray/_private/accelerators/tpu.py:71): detects chips per host, pod
type, and slice membership; sets chip-visibility env vars for workers; and
synthesizes slice-level resources so gang scheduling can target whole
slices (tpu.py:314,381). Detection order: explicit env override → GKE-style
TPU env vars → JAX probe (only if jax already imported) → none.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Optional

# v5e host topology default: 4 chips/host (v4: 4, v5p: 4; v5e can be 1/4/8)
DEFAULT_CHIPS_PER_HOST = 4


def detect_tpu_chips() -> int:
    """Number of TPU chips attached to this host."""
    env = os.environ.get("RAY_TPU_NUM_TPUS")
    if env is not None:
        return int(env)
    bounds = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")  # e.g. "2,2,1"
    if bounds:
        n = 1
        for part in bounds.split(","):
            n *= int(part)
        return n
    # JAX probe only when jax is already loaded — the raylet should not drag
    # in libtpu just to count chips.
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return len([d for d in jax.devices()
                        if d.platform in ("tpu", "axon")])
        except Exception:
            return 0
    return 0


def tpu_pod_type() -> Optional[str]:
    """E.g. "v5litepod-64" (reference: tpu.py accelerator type from GCE
    metadata / GKE env)."""
    return os.environ.get("TPU_ACCELERATOR_TYPE") or \
        os.environ.get("RAY_TPU_POD_TYPE")


def tpu_slice_id() -> str:
    """Identity of the slice this host belongs to. Hosts in the same slice
    share an ICI domain; the SLICE placement strategy gangs over it."""
    return os.environ.get("TPU_WORKER_HOSTNAMES",
                          os.environ.get("RAY_TPU_SLICE_ID", ""))


def tpu_worker_id() -> int:
    return int(os.environ.get("TPU_WORKER_ID", "0"))


def num_hosts_in_slice() -> int:
    pod = tpu_pod_type()
    if not pod:
        return 1
    try:
        chips = int(pod.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 1
    return max(1, chips // DEFAULT_CHIPS_PER_HOST)


def slice_resources() -> Dict[str, float]:
    """Synthesized resources for gang scheduling: per-host chips plus the
    slice-head marker on worker 0 (reference: tpu.py:314,381
    `TPU-{pod_type}-head`)."""
    res: Dict[str, float] = {}
    chips = detect_tpu_chips()
    if chips:
        res["TPU"] = float(chips)
        pod = tpu_pod_type()
        if pod:
            res[f"TPU-{pod}"] = float(chips)
            if tpu_worker_id() == 0:
                res[f"TPU-{pod}-head"] = 1.0
    return res


def set_visible_chips_env(env: Dict[str, str], chip_ids: list) -> None:
    """Restrict a worker process to specific chips (reference: tpu.py:31
    TPU_VISIBLE_CHIPS)."""
    env["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chip_ids)
    env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = f"1,{len(chip_ids)},1"
