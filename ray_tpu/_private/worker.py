"""Global worker + the synchronous public core API.

Equivalent of the reference's worker module (python/ray/_private/worker.py):
``init`` (:1225) boots or joins a cluster and connects a driver CoreWorker;
``get``/``put``/``wait`` (:2551+) bridge the synchronous user thread onto the
CoreWorker's io loop; ``shutdown`` (:1824) tears the session down.
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Any, List, Optional, Sequence, Union

from ray_tpu.core import rpc
from ray_tpu.core.config import Config
from ray_tpu.core.ids import ActorID, NodeID, WorkerID
from ray_tpu.core.object_ref import ObjectRef

logger = logging.getLogger(__name__)

_global_worker: Optional["Worker"] = None
_init_lock = threading.Lock()
_FALLBACK = object()  # sentinel: _get_fast defers to the loop-based path


class Worker:
    """Driver- or executor-side facade over a CoreWorker."""

    def __init__(self, core, io_thread=None, node=None,
                 namespace: str = "default"):
        self.core = core
        self.io = io_thread
        self.node = node
        self.namespace = namespace
        self.loop = core.loop

    # -- bridging helpers --------------------------------------------------
    def _run(self, coro, timeout: Optional[float] = None):
        import asyncio

        from ray_tpu._private.core_worker import (_EXEC_TL,
                                                  InlineUnsafeError)

        # Executor-thread observation: a task using the sync API can
        # never be inlined onto the io loop (see _run_timed_sync).
        key = getattr(_EXEC_TL, "key", None)
        if key is not None:
            self.core._exec_sync_api_keys.add(key)
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self.loop:
            if getattr(self.core, "_inline_active", False):
                coro.close()
                raise InlineUnsafeError(
                    "task uses the sync blocking API; retrying on the "
                    "executor path")
            raise RuntimeError(
                "sync API called from the io loop; use the async variants")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    # -- public ops --------------------------------------------------------
    def get(self, refs, timeout: Optional[float] = None):
        import asyncio

        from ray_tpu.dag.compiled_dag import CompiledDAGRef

        # Observation: every get() — including the _get_fast path that
        # never reaches _run — marks the running task key as
        # sync-API-using, so such keys are barred from inlining BEFORE
        # they ever qualify (no retry, no duplicated side effects).
        from ray_tpu._private.core_worker import _EXEC_TL

        obs_key = getattr(_EXEC_TL, "key", None)
        if obs_key is not None:
            self.core._exec_sync_api_keys.add(obs_key)
        # get() from a task inlined on the io loop would deadlock in the
        # fast path's blocking wait — bail to the executor retry instead
        # (see core_worker._run_timed_sync). Unreachable for keys that
        # used the sync API during observation; the retry re-executes
        # from the start (at-least-once task semantics).
        if getattr(self.core, "_inline_active", False):
            from ray_tpu._private.core_worker import InlineUnsafeError

            try:
                on_loop = asyncio.get_running_loop() is self.loop
            except RuntimeError:
                on_loop = False  # not on the loop thread
            if on_loop:
                raise InlineUnsafeError(
                    "task uses the sync blocking API; retrying on "
                    "the executor path")
        if self.core._fast_keys:
            self.core.flush_fast_channels()
        single = isinstance(refs, (ObjectRef, CompiledDAGRef))
        ref_list = [refs] if single else list(refs)
        if any(isinstance(r, CompiledDAGRef) for r in ref_list):
            # Compiled-DAG results read their channels directly
            # (reference: ray.get on CompiledDAGRef).  Mixed lists resolve
            # each kind via its own path under one shared deadline; the
            # ObjectRef subset keeps the batched fast path.
            import time as _time

            deadline = (_time.monotonic() + timeout
                        if timeout is not None else None)

            def remaining():
                if deadline is None:
                    return None
                return max(deadline - _time.monotonic(), 0.001)

            obj_refs = [r for r in ref_list if not isinstance(r,
                                                              CompiledDAGRef)]
            obj_values = iter(self.get(obj_refs, remaining())
                              if obj_refs else ())
            values = [r.get(remaining()) if isinstance(r, CompiledDAGRef)
                      else next(obj_values) for r in ref_list]
            return values[0] if single else values
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() takes ObjectRefs, got {type(r)}")
        values = self._get_fast(ref_list, timeout)
        if values is _FALLBACK:
            values = self._run(self.core.get_objects(ref_list, timeout))
        return values[0] if single else values

    def _get_fast(self, ref_list, timeout: Optional[float]):
        """Synchronous fast path: objects owned by this worker whose values
        land in the in-process memory store (small task returns, actor call
        replies) are read and deserialized directly on the calling thread —
        zero io-loop round trips per get. Anything else (plasma objects,
        borrowed refs, lost objects needing reconstruction) falls back to
        the loop-based CoreWorker.get_objects path.
        """
        import time as _time

        from ray_tpu.core import serialization as ser

        core = self.core
        store = core.memory_store
        deadline = (_time.monotonic() + timeout
                    if timeout is not None else None)
        out = []
        for ref in ref_list:
            data = store.get_if_exists(ref.id)
            while data is None:
                if store.is_in_plasma(ref.id):
                    return _FALLBACK
                if not core.reference_counter.is_owned(ref.id):
                    return _FALLBACK
                if ref.id.task_id() not in core._pending_tasks:
                    # Completed-but-absent (evicted / needs reconstruction)
                    # or just landed: one cheap recheck, else slow path.
                    data = store.get_if_exists(ref.id)
                    if data is None:
                        return _FALLBACK
                    break
                remaining = (None if deadline is None
                             else deadline - _time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise ser.GetTimeoutError(f"get timed out on {ref}")
                store.wait_ready_sync(
                    ref.id, min(remaining, 1.0) if remaining else 1.0)
                data = store.get_if_exists(ref.id)
            value = ser.loads(data)
            if isinstance(value, (ser.RayTaskError, ser.ActorDiedError,
                                  ser.WorkerCrashedError,
                                  ser.TaskCancelledError,
                                  ser.ObjectLostError)):
                raise value
            out.append(value)
        return out

    def put(self, value) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("put() of an ObjectRef is not allowed")
        return self._run(self.core.put_object(value))

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        if self.core._fast_keys:
            self.core.flush_fast_channels()
        refs = list(refs)
        if num_returns > len(refs):
            raise ValueError("num_returns exceeds the number of refs")
        return self._run(self.core.wait_objects(
            refs, num_returns, timeout, fetch_local))

    def as_future(self, ref: ObjectRef):
        import asyncio

        return asyncio.run_coroutine_threadsafe(
            self.core.get_objects([ref]), self.loop)

    async def get_async(self, ref: ObjectRef):
        return (await self.core.get_objects([ref]))[0]

    @property
    def reference_counter(self):
        return self.core.reference_counter

    # Job-level runtime env (init(runtime_env=...)): merged under every
    # task/actor's own env (per-call wins on conflicts, env_vars merge).
    # Stored in URI form (packages uploaded once at init) and published
    # to the GCS KV so NESTED tasks — submitted from executor workers —
    # inherit it too. Cached PER JOB ID: pooled executor workers are
    # re-leased across jobs and must not serve a stale job's env.
    _job_envs: Optional[dict] = None

    def _get_job_env(self) -> Optional[dict]:
        from ray_tpu.core import serialization as ser

        # Executor workers carry a nil job id; the submitting job is
        # the one of the task currently executing.
        job_id = self.core.job_id
        if (job_id is None or job_id.is_nil()) and \
                self.core._current_task is not None:
            job_id = self.core._current_task.job_id
        if job_id is None or job_id.is_nil():
            return None  # no job context
        if self._job_envs is None:
            self._job_envs = {}
        key = job_id.binary()
        if key not in self._job_envs:
            raw = self.gcs_call("kv_get", {"ns": b"job_env", "key": key})
            self._job_envs[key] = ser.loads(raw) if raw else None
        return self._job_envs[key]

    def set_job_runtime_env(self, env: Optional[dict]) -> None:
        """Driver-side: prepare (upload packages) once and publish."""
        if not env:
            return
        from ray_tpu._private.runtime_env import prepare_runtime_env
        from ray_tpu.core import serialization as ser

        prepared = prepare_runtime_env(env, self.gcs_call)
        if self._job_envs is None:
            self._job_envs = {}
        self._job_envs[self.core.job_id.binary()] = prepared
        self.gcs_call("kv_put", {
            "ns": b"job_env", "key": self.core.job_id.binary(),
            "value": ser.dumps(prepared)})

    def _prepare_env_opts(self, opts) -> dict:
        if opts.get("runtime_env") is None and self._job_envs is not None:
            # Hot path: job env already resolved and empty, no per-call
            # env — nothing to merge or package.
            key = self.core.job_id.binary() if self.core.job_id else None
            if key in self._job_envs and not self._job_envs[key]:
                return opts
        from ray_tpu._private.runtime_env import (merge_runtime_envs,
                                                  prepare_runtime_env)

        env = merge_runtime_envs(self._get_job_env(),
                                 opts.get("runtime_env"))
        if env:
            opts = dict(opts)
            # Job-env packages are already URI-form; only the per-call
            # env's local paths get packaged here.
            opts["runtime_env"] = prepare_runtime_env(env, self.gcs_call)
        return opts

    def submit_task(self, descriptor, args, kwargs, opts) -> List[ObjectRef]:
        opts = self._prepare_env_opts(opts)
        # Caller-thread fast path: no io-loop round trip per .remote().
        return self.core.submit_task_sync(descriptor, args, kwargs, opts)

    def create_actor(self, descriptor, args, kwargs, opts) -> ActorID:
        opts = self._prepare_env_opts(opts)
        if opts.get("name") or opts.get("lifetime") == "detached":
            # Named/detached: registration stays synchronous so name
            # conflicts raise at .remote() (reference semantics).
            return self._run(
                self.core.create_actor(descriptor, args, kwargs, opts))
        # Anonymous: caller-thread fast path, registration pipelined.
        return self.core.create_actor_sync(descriptor, args, kwargs, opts)

    def submit_actor_task(self, actor_id, method, args, kwargs, opts):
        return self.core.submit_actor_task_sync(
            actor_id, method, args, kwargs, opts)

    def export(self, fn):
        return self.core.function_manager.export(fn)

    def gcs_call(self, method: str, data=None, timeout: float = 30.0):
        import time as _time

        from ray_tpu.core.rpc import ConnectionLost

        # Ride through GCS restarts: the core reconnects in the
        # background (core_worker._reconnect_gcs); retry on the fresh
        # connection until the deadline.
        deadline = _time.monotonic() + timeout
        while True:
            try:
                return self._run(
                    self.core.gcs.call(method, data, timeout=timeout))
            except (ConnectionLost, ConnectionError, OSError):
                if _time.monotonic() > deadline:
                    raise
                _time.sleep(0.3)


def global_worker() -> Worker:
    if _global_worker is None:
        raise RuntimeError(
            "ray_tpu has not been initialized; call ray_tpu.init() first")
    return _global_worker


def global_worker_or_none() -> Optional[Worker]:
    return _global_worker


def is_initialized() -> bool:
    return _global_worker is not None


def _attach_executor_worker(core) -> None:
    """Called inside worker processes so user task code can use the API."""
    global _global_worker
    _global_worker = Worker(core)


def init(address: Optional[str] = None, *,
         resources: Optional[dict] = None,
         num_cpus: Optional[float] = None,
         num_tpus: Optional[float] = None,
         object_store_memory: Optional[int] = None,
         namespace: str = "default",
         runtime_env: Optional[dict] = None,
         system_config: Optional[dict] = None,
         ignore_reinit_error: bool = False,
         _node_kwargs: Optional[dict] = None) -> "RuntimeContext":
    """Start a new single-node cluster (head) or connect to an existing one.

    Reference: python/ray/_private/worker.py:1225.
    """
    global _global_worker
    with _init_lock:
        if _global_worker is not None:
            if ignore_reinit_error:
                return get_runtime_context()
            raise RuntimeError("ray_tpu.init() called twice")
        if address and address.startswith("ray://"):
            # Client mode: the driver runs remotely behind a proxy
            # (reference: Ray Client, python/ray/util/client/).
            from ray_tpu.util.client.worker import ClientWorker

            host, _, port = address[len("ray://"):].partition(":")
            _global_worker = ClientWorker(host, int(port or 10001))
            if runtime_env:
                _global_worker.set_job_runtime_env(runtime_env)
            return _global_worker
        import asyncio

        from ray_tpu._private.core_worker import DRIVER, CoreWorker
        from ray_tpu._private.node import Node

        config = Config.from_env(system_config)
        if object_store_memory:
            config.object_store_memory = object_store_memory
        node = None
        if address is None:
            res = dict(resources or {})
            if num_cpus is not None:
                res["CPU"] = float(num_cpus)
            if num_tpus is not None:
                res["TPU"] = float(num_tpus)
            node = Node(config, resources=res or None,
                        **(_node_kwargs or {}))
            node.start()
            gcs_address = node.gcs_address
            raylet_address = node.raylet_address
            store_path = node.store_path
            session_dir = node.session_dir
        else:
            gcs_address = address
            raylet_address, store_path, session_dir = \
                _find_local_node(address, config)
            if raylet_address is None:
                raise RuntimeError(
                    "no alive raylet found on this host for cluster "
                    f"{address}; drivers must run on a cluster node "
                    "(start one with Cluster.add_node or ray_tpu.init())")
        io = rpc.EventLoopThread()
        core = CoreWorker(
            mode=DRIVER, gcs_address=gcs_address, config=config,
            loop=io.loop, raylet_address=raylet_address,
            store_path=store_path, session_dir=session_dir)
        try:
            asyncio.run_coroutine_threadsafe(core.connect(), io.loop).result(60)
        except Exception:
            if node is not None:
                node.shutdown()
            io.stop()
            raise
        _global_worker = Worker(core, io_thread=io, node=node,
                                namespace=namespace)
        _global_worker.set_job_runtime_env(runtime_env)
        atexit.register(shutdown)
        return get_runtime_context()


def _find_local_node(address: str, config: Config):
    """Join an existing cluster: locate (or lack) a raylet on this host."""
    import asyncio

    async def probe():
        host, port = address.rsplit(":", 1)
        conn = await rpc.connect(host, int(port), timeout=10.0)
        nodes = await conn.call("get_nodes")
        await conn.close()
        hostname = os.uname().nodename
        for n in nodes:
            if n["hostname"] == hostname and n["state"] == "ALIVE" and \
                    os.path.exists(n["store_path"]):
                return n["address"], n["store_path"]
        return None, None

    raylet_address, store_path = asyncio.run(probe())
    return raylet_address, store_path, config.temp_dir


def shutdown() -> None:
    global _global_worker
    with _init_lock:
        w = _global_worker
        if w is None:
            return
        _global_worker = None
        if getattr(w, "mode", None) == "client":
            w.disconnect()
            return
        import asyncio

        try:
            asyncio.run_coroutine_threadsafe(
                w.core.disconnect(), w.loop).result(5)
        except Exception:
            pass
        if w.io is not None:
            w.io.stop()
        if w.node is not None:
            w.node.shutdown()
        try:
            atexit.unregister(shutdown)
        except Exception:
            pass


def get(refs, *, timeout: Optional[float] = None):
    return global_worker().get(refs, timeout=timeout)


def put(value) -> ObjectRef:
    return global_worker().put(value)


def wait(refs, *, num_returns: int = 1, timeout: Optional[float] = None,
         fetch_local: bool = True):
    return global_worker().wait(refs, num_returns, timeout, fetch_local)


def cluster_resources() -> dict:
    """Total resources across alive nodes (reference:
    ray.cluster_resources)."""
    return global_worker().gcs_call("cluster_resources")["total"]


def available_resources() -> dict:
    """Currently-available resources (reference:
    ray.available_resources)."""
    return global_worker().gcs_call("cluster_resources")["available"]


def nodes() -> list:
    """Node table (reference: ray.nodes)."""
    from ray_tpu.util import state

    return state.list_nodes()


def kill(actor, *, no_restart: bool = True) -> None:
    from ray_tpu.core.actor import ActorHandle

    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() takes an ActorHandle")
    w = global_worker()
    if getattr(w, "mode", None) == "client":
        w.kill_actor(actor._actor_id, no_restart)
        return
    w._run(w.core.kill_actor(actor._actor_id, no_restart))


def cancel(ref: ObjectRef, *, force: bool = False) -> bool:
    """Best-effort: queued-but-unsent tasks are dropped (True); tasks
    already dispatched keep running (False).
    Reference: CoreWorker::CancelTask non-force path."""
    w = global_worker()
    return w._run(w.core.cancel_task(ref))


class RuntimeContext:
    def __init__(self, worker: Worker):
        self._worker = worker

    @property
    def job_id(self):
        return self._worker.core.job_id

    @property
    def node_id(self) -> Optional[NodeID]:
        return self._worker.core.node_id

    @property
    def worker_id(self) -> WorkerID:
        return self._worker.core.worker_id

    @property
    def gcs_address(self) -> str:
        return self._worker.core.gcs_address

    @property
    def current_actor_id(self) -> Optional[ActorID]:
        return self._worker.core._local_actor_id

    @property
    def was_current_actor_reconstructed(self) -> bool:
        return False

    def get_task_id(self):
        spec = self._worker.core._current_task
        return spec.task_id if spec else None

    def get_trace_id(self) -> str:
        """Trace id of the current call chain (reference: OTel span
        context propagated through task metadata,
        tracing_helper.py:326). Empty outside task execution."""
        spec = self._worker.core._current_task
        if spec is not None and spec.trace_ctx:
            return spec.trace_ctx.get("trace_id", "")
        return ""

    def get_parent_span_id(self) -> str:
        spec = self._worker.core._current_task
        if spec is not None and spec.trace_ctx:
            return spec.trace_ctx.get("parent_span_id", "")
        return ""

    def cluster_resources(self) -> dict:
        return self._worker.gcs_call("cluster_resources")


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(global_worker())
