"""CoreWorker — the in-process runtime for drivers and workers.

Equivalent of the reference's CoreWorker (src/ray/core_worker/
core_worker.h:295) and its transport layer:
- put/get/wait over a two-tier store: in-process memory store for small
  objects (store_provider/memory_store/memory_store.h:43) + node-local shm
  store for large ones, with cross-node pulls via the raylet.
- Normal-task submission through worker leases with pipelining
  (transport/normal_task_submitter.cc:24 — lease per scheduling key, push
  tasks directly to the leased worker, spillback handling).
- Actor creation via the GCS actor manager; actor tasks pushed directly to
  the actor's worker over a persistent connection, in submission order
  (transport/actor_task_submitter).
- Ownership-based distributed refcounting (reference_count.cc): the caller
  owns task returns and puts; borrowers notify the owner; when an object
  goes out of scope the owner frees it everywhere.
- Task execution (worker mode) with per-actor ordered queues, concurrency
  groups (max_concurrency), and inline small-return replies.
- Lineage: owned objects record their producing TaskSpec; a lost object is
  reconstructed by resubmitting that task (object_recovery_manager.h:106).
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import logging
import os
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

import msgpack

from ray_tpu.core import rpc
from ray_tpu.core import serialization as ser
from ray_tpu.core.config import Config
from ray_tpu.core.ids import (ActorID, JobID, NodeID, ObjectID, TaskID,
                              WorkerID)
from ray_tpu.core.generator import (STREAMING, ObjectRefGenerator,
                                    StreamState)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.shm_client import ShmClient, StoreFullError
from ray_tpu.core.task_spec import (ACTOR_CREATION_TASK, ACTOR_TASK,
                                    ARG_REF, ARG_VALUE, NORMAL_TASK,
                                    FunctionDescriptor, TaskSpec)
from ray_tpu._private.function_manager import FunctionManager
from ray_tpu._private.memory_store import MemoryStore
from ray_tpu._private.reference_counter import ReferenceCounter

logger = logging.getLogger(__name__)

DRIVER, WORKER = "driver", "worker"

# Executor-thread marker: which task key is currently running under
# observation (lets Worker._run flag keys that use the sync API — those
# can never run inline on the io loop).
_EXEC_TL = threading.local()


class InlineUnsafeError(RuntimeError):
    """Sync blocking API called from a task running inline on the io
    loop — the task is retried on the executor path and its key is
    permanently barred from inlining."""


class _Lease:
    __slots__ = ("lease_id", "address", "conn", "inflight", "raylet_address",
                 "fast_addr")

    def __init__(self, lease_id: bytes, address: str, conn: rpc.Connection,
                 raylet_address: str, fast_addr: str = ""):
        self.lease_id = lease_id
        self.address = address
        self.conn = conn
        self.inflight = 0
        self.raylet_address = raylet_address
        self.fast_addr = fast_addr


class _FastKey:
    """A scheduling key in fastlane mode: one leased worker owned by a
    native channel; submissions ride the caller's thread, replies the
    channel's pump thread — the io loop only brokers the lease."""

    __slots__ = ("key", "channel", "lease", "deact_scheduled")

    def __init__(self, key: tuple, channel, lease: _Lease):
        self.key = key
        self.channel = channel
        self.lease = lease
        self.deact_scheduled = False

    def submit_spec(self, spec: TaskSpec) -> bool:
        wire = spec.to_wire()
        if spec.is_streaming or \
                any(kind == ARG_REF for kind, _p, _o in spec.args):
            # Solo frame, not batched:
            # - A dependent task must NEVER share a batch with the task
            #   producing its argument: the batch reply (which delivers
            #   the dependency's result to this driver) is only sent
            #   once EVERY task in the batch finishes — the dependent
            #   task would wait on a result its own batch withholds.
            # - A streaming task blocks its dispatcher until the stream
            #   ends; co-batched tasks behind an unbounded generator
            #   would never reply.
            # Flush first so upstream results travel ahead.
            self.channel.flush()
            return self.channel.submit(
                msgpack.packb({"task": wire}, use_bin_type=True),
                ("task", spec, self.key))
        return self.channel.submit_batched(wire, ("task", spec, self.key))


class _SchedulingKeyState:
    __slots__ = ("queue", "leases", "requests_inflight", "duration_ema")

    def __init__(self):
        self.queue: List[TaskSpec] = []
        self.leases: List[_Lease] = []
        self.requests_inflight = 0
        # EMA of worker-reported execution time for this key; None until
        # the first reply. Gates pipelining (see _pump_scheduling_key).
        self.duration_ema: Optional[float] = None


class _ActorState:
    def __init__(self):
        self.address: str = ""
        self.conn: Optional[rpc.Connection] = None
        self.state: str = "PENDING"
        self.seqno = 0
        # Guards seqno increments: submission happens on the caller's
        # thread (submit_actor_task_sync), possibly several at once.
        self.seq_lock = threading.Lock()
        self.death_cause = ""
        self.lock = asyncio.Lock()
        # Fastlane routing (native task path): the worker's fastlane port,
        # a FastChannel once connected, and a count of in-flight pushes on
        # the asyncio path — the channel engages only when that count is
        # zero, so per-caller FIFO order survives the transition.
        self.fast_addr: str = ""
        self.max_concurrency: int = 1
        self.channel = None
        self.fast_disabled = False
        self.loop_inflight = 0
        # Async registration (anonymous actors register fire-and-forget;
        # the first connection waits for the GCS ack to land).
        self.register_done: Optional[asyncio.Event] = None
        self.register_error: Optional[BaseException] = None


class _LocalActor:
    """Executor-side state for the actor instance hosted in this worker.

    Ordering invariant: tasks from one caller arrive over one TCP connection
    and are turned into asyncio tasks in arrival order by the connection's
    read loop; with max_concurrency=1 the semaphore admits them FIFO, so
    per-caller submission order is execution order (reference: actor
    scheduling queues, transport/scheduling_queue).
    """

    def __init__(self, instance, max_concurrency: int):
        self.instance = instance
        self.semaphore = asyncio.Semaphore(max(max_concurrency, 1))
        self.max_concurrency = max_concurrency


class CoreWorker:
    def __init__(self, mode: str, gcs_address: str, config: Config,
                 loop: asyncio.AbstractEventLoop,
                 raylet_address: Optional[str] = None,
                 store_path: Optional[str] = None,
                 node_id: Optional[NodeID] = None,
                 session_dir: str = "/tmp/ray_tpu",
                 worker_id: Optional[WorkerID] = None):
        self.mode = mode
        self.config = config
        self.loop = loop
        self.gcs_address = gcs_address
        self.raylet_address = raylet_address
        self.store_path = store_path
        self.node_id = node_id
        self.session_dir = session_dir
        self.worker_id = worker_id or WorkerID.from_random()
        self.job_id: Optional[JobID] = None
        self.address: str = ""

        self.memory_store = MemoryStore(loop)
        self.plasma: Optional[ShmClient] = None
        self.reference_counter = ReferenceCounter(
            on_object_out_of_scope=self._on_object_out_of_scope,
            notify_owner_ref_removed=self._notify_owner_ref_removed)
        self.function_manager = FunctionManager(self._kv_put_sync,
                                                self._kv_get_sync)
        self.gcs: Optional[rpc.Connection] = None
        self.raylet: Optional[rpc.Connection] = None
        self._server: Optional[rpc.Server] = None
        self._scheduling_keys: Dict[tuple, _SchedulingKeyState] = {}
        self._actors: Dict[ActorID, _ActorState] = {}
        self._peer_conns: Dict[str, rpc.Connection] = {}
        self._task_counter = 0
        self._current_task: Optional[TaskSpec] = None
        # executor-side
        self._local_actor: Optional[_LocalActor] = None
        self._local_actor_id: Optional[ActorID] = None
        self._executor = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="task_exec")
        # Batched user-code dispatch (see _exec_pump): amortizes the
        # cross-thread wake cost of run_in_executor over bursts of
        # pipelined tasks. Direct mode for multi-threaded actors.
        self._exec_lock = threading.Lock()
        self._exec_queue: "collections.deque" = collections.deque()
        self._exec_pump_running = False
        self._exec_direct = False
        # Inline-on-loop gating: key -> [duration EMA, observation count].
        # A key becomes inline-eligible only after several observed-fast
        # executor runs during which it never touched the sync blocking
        # API (those keys land in _exec_sync_api_keys and never inline).
        self._exec_ema: Dict[Any, list] = {}
        self._exec_sync_api_keys: set = set()
        self._inline_active = False
        if config.gil_switch_interval_s > 0:
            # Single-core hosts: the default 5 ms GIL switch interval
            # stalls the io loop whenever the executor thread holds the
            # GIL mid-task. A sub-ms interval keeps message handling
            # responsive (reference relies on true C++ io threads here).
            import sys as _sys

            _sys.setswitchinterval(config.gil_switch_interval_s)
        self._pending_tasks: Dict[TaskID, TaskSpec] = {}
        self._streams: Dict[TaskID, StreamState] = {}
        self._stream_cancels: set = set()  # executor-side cancel flags
        self._stream_producing: set = set()  # tasks mid-produce-loop
        self._stream_acked: Dict[TaskID, int] = {}  # consumer progress
        self._stream_ack_events: Dict[TaskID, asyncio.Event] = {}
        self._task_events: List[dict] = []
        # Events are recorded from user threads (submit_task_sync) AND
        # the io loop; the swap-on-flush must be atomic across them.
        self._task_events_lock = threading.Lock()
        self._task_events_last_flush: float = 0.0
        self._borrowed_notified: set = set()
        self._should_exit = asyncio.Event()
        # --- fastlane (native task path) ---
        self.fast_address: str = ""
        self._fl_server = None
        self._fl_dispatchers: List[threading.Thread] = []
        self._fast_keys: Dict[tuple, _FastKey] = {}
        # Serializes user-code execution across the fastlane dispatcher
        # threads, the executor pump, and the inline-on-loop path (the
        # loop only try-acquires — it must never block on this).
        self._exec_mutex = threading.RLock()
        self._env_seen = False  # a scoped runtime_env task has run here
        self._direct_inflight = 0
        self._direct_lock = threading.Lock()
        self._fl_coro_cache: Dict[str, bool] = {}
        self._fl_actor_simple: Optional[bool] = None

    # ---------------------------------------------------------------- setup
    async def connect(self) -> None:
        import time as _time

        _t0 = _time.perf_counter()
        _trace = os.environ.get("RAY_TPU_TRACE_STARTUP")

        def _tr(msg):
            if _trace:
                print(f"CTRACE {os.getpid()} "
                      f"+{_time.perf_counter() - _t0:.3f} {msg}",
                      flush=True)

        self._server = rpc.Server(self, "127.0.0.1", 0)
        port = await self._server.start()
        _tr("rpc server up")
        self.address = f"127.0.0.1:{port}"
        ghost, gport = self.gcs_address.rsplit(":", 1)
        # Generous first-connect budget: under spawn storms the control
        # processes' loops lag and accepts queue up; 10s flakes.
        self.gcs = await rpc.connect(
            ghost, int(gport), handler=self._on_pubsub, name="->gcs",
            timeout=self.config.worker_register_timeout_s)
        self.gcs.on_close = self._on_gcs_close
        _tr("gcs connected")
        if self.mode == DRIVER:
            r = await self.gcs.call("register_job",
                                    {"driver_address": self.address})
            self.job_id = JobID(r["job_id"])
            await self.gcs.call("subscribe", {"channel": "actors"})
            if self.config.log_to_driver:
                await self.gcs.call("subscribe", {"channel": "logs"})
        else:
            self.job_id = JobID.nil()
        if self.mode == WORKER and self.config.fastlane_enabled:
            try:
                from ray_tpu.core.fastlane import FastlaneServer

                self._fl_server = FastlaneServer()
                self.fast_address = f"127.0.0.1:{self._fl_server.port}"
                for i in range(2):
                    t = threading.Thread(
                        target=self._fastlane_dispatch_loop,
                        name=f"fl-dispatch-{i}", daemon=True)
                    t.start()
                    self._fl_dispatchers.append(t)
            except Exception:
                logger.exception(
                    "fastlane server failed to start; using rpc path only")
                self._fl_server = None
                self.fast_address = ""
            _tr("fastlane up")
        if self.raylet_address:
            rhost, rport = self.raylet_address.rsplit(":", 1)
            self.raylet = await rpc.connect(
                rhost, int(rport), handler=self._on_raylet_message,
                name="->raylet",
                timeout=self.config.worker_register_timeout_s)
            r = await self.raylet.call("register_worker", {
                "worker_id": self.worker_id.binary(),
                "address": self.address,
                "fast_address": self.fast_address,
                "pid": os.getpid(),
            })
            if self.node_id is None:
                self.node_id = NodeID(r["node_id"])
            _tr("raylet registered")
            if self.mode == WORKER:
                # A worker whose raylet dies must exit, not linger as an
                # orphan (reference: workers poll the raylet socket and
                # die with it).
                self.raylet.on_close = \
                    lambda conn: self._should_exit.set()
        if self.store_path:
            self.plasma = ShmClient(self.store_path)
            if self.mode == DRIVER:
                # Per-process PTE prefault of the hot arena prefix (the
                # raylet populates the tmpfs pages; this maps them into
                # the driver, whose puts dominate). Workers skip it —
                # they churn through leases constantly.
                self.plasma.prefault(1 << 30)
        if self.config.task_events_enabled:
            self._task_event_flusher = asyncio.get_running_loop(
            ).create_task(self._task_event_flush_loop())
        _tr("connect done")

    def _on_gcs_close(self, conn: rpc.Connection) -> None:
        if not self._should_exit.is_set() and self.loop.is_running():
            self.loop.create_task(self._reconnect_gcs())

    async def _reconnect_gcs(self) -> None:
        """The GCS died (head restart): reconnect, re-subscribe, and — for
        drivers — re-attach the job so driver-disconnect semantics keep
        working (reference: workers ride out GCS restarts; state is
        restored from table storage)."""
        ghost, gport = self.gcs_address.rsplit(":", 1)
        delay = 0.5
        while not self._should_exit.is_set():
            conn = None
            try:
                conn = await rpc.connect(ghost, int(gport),
                                         handler=self._on_pubsub,
                                         name="->gcs")
                if self.mode == DRIVER:
                    await conn.call("reattach_job", {
                        "job_id": self.job_id.binary(),
                        "driver_address": self.address})
                    await conn.call("subscribe", {"channel": "actors"})
                    if self.config.log_to_driver:
                        await conn.call("subscribe", {"channel": "logs"})
            except Exception:
                if conn is not None:
                    await conn.close()
                # Keep trying (backoff-capped) until shutdown: the head
                # may come back minutes later, and gcs_call retries lean
                # on this loop eventually landing a fresh connection.
                await asyncio.sleep(delay)
                delay = min(delay * 1.5, 5.0)
                continue
            conn.on_close = self._on_gcs_close
            self.gcs = conn
            logger.info("reconnected to restarted GCS")
            return

    async def _task_event_flush_loop(self) -> None:
        """Periodic flush so trailing events (sub-batch-size bursts after
        the last task) still reach the GCS (reference: TaskEventBuffer's
        timer-driven flush)."""
        while not self._should_exit.is_set():
            await asyncio.sleep(1.0)
            if self._task_events:
                self._flush_task_events()

    async def disconnect(self) -> None:
        self._should_exit.set()  # no GCS reconnect attempts during teardown
        flusher = getattr(self, "_task_event_flusher", None)
        if flusher is not None:
            flusher.cancel()
        if self._task_events and self.gcs and not self.gcs.closed:
            events, self._task_events = self._task_events, []
            try:
                await self.gcs.call("report_task_events",
                                    {"events": events})
            except Exception:
                pass
        self._executor.shutdown(wait=False, cancel_futures=True)
        for st in self._actors.values():
            if st.channel is not None:
                st.channel.close()
        for fk in list(self._fast_keys.values()):
            fk.channel.close()
        self._fast_keys.clear()
        if self._fl_server is not None:
            self._fl_server.shutdown()
            # Short join: dispatchers wake from next() within ~ms of
            # shutdown; one mid-execution user task shouldn't add
            # seconds to every (SIGTERM'd) worker teardown — the native
            # server is leaked in that case, and the process is exiting.
            for t in self._fl_dispatchers:
                t.join(timeout=0.1)
            if all(not t.is_alive() for t in self._fl_dispatchers):
                self._fl_server.close()
        for conn in list(self._peer_conns.values()):
            await conn.close()
        if self._server:
            await self._server.close()
        if self.raylet:
            await self.raylet.close()
        if self.gcs:
            await self.gcs.close()
        # The shm mapping may only be freed when no thread can still
        # call into it: executor / fastlane dispatcher threads mid-user-
        # code would segfault on a freed handle (observed at 400-actor
        # kill scale). Workers are exiting anyway — leak the mapping
        # there. DRIVERS are long-lived (pytest runs dozens of
        # init/shutdown cycles in one process), so close when every
        # worker thread is verifiably quiesced within a bounded join.
        if self.plasma is not None and self.mode == DRIVER:
            threads = list(self._fl_dispatchers) + \
                list(getattr(self._executor, "_threads", []))
            # Plasma puts also run on the LOOP's default executor
            # (_put_plasma -> run_in_executor(None, ...)): those threads
            # must quiesce too or an in-flight put races the close. They
            # idle on the work queue until shutdown — signal it NOW
            # (idle threads wake and exit; a mid-put thread finishes its
            # item first), else the quiesce check below can never pass.
            default_exec = getattr(self.loop, "_default_executor", None)
            if default_exec is not None:
                default_exec.shutdown(wait=False)
                threads += list(getattr(default_exec, "_threads", []))
            # One shared deadline: per-thread timeouts would stack and
            # block the loop for 0.2s x thread count.
            deadline = time.monotonic() + 0.25
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            if all(not t.is_alive() for t in threads):
                try:
                    self.plasma.close()
                except Exception:
                    pass
                self.plasma = None

    async def _on_pubsub(self, method: str, data, conn) -> None:
        if method == "publish" and data["channel"] == "logs":
            # Worker stdout/stderr streamed to the driver console
            # (reference: log_monitor.py:103 -> print_to_stdstream).
            # Only this job's workers (None = unleased worker chatter).
            import sys as _sys

            msg = data["data"]
            owner = msg.get("job_id")
            if owner is not None and self.job_id is not None and \
                    owner != self.job_id.binary():
                return
            prefix = f"(pid={msg.get('pid')}) "
            for line in msg.get("lines", []):
                _sys.stderr.write(prefix + line + "\n")
            _sys.stderr.flush()
            return
        if method == "publish" and data["channel"] == "actors":
            view = data["data"]
            aid = ActorID(view["actor_id"])
            st = self._actors.get(aid)
            if st is not None:
                st.state = view["state"]
                st.death_cause = view.get("death_cause", "")
                st.max_concurrency = view.get("max_concurrency",
                                              st.max_concurrency)
                if view["state"] == "ALIVE" and view["address"] != st.address:
                    st.address = view["address"]
                    st.fast_addr = view.get("fast_address", "")
                    if st.conn:
                        await st.conn.close()
                        st.conn = None
                    if st.channel is not None:
                        st.channel.close()  # restarted actor: reconnect lazily
                        st.channel = None
                elif view["state"] == "ALIVE":
                    st.fast_addr = view.get("fast_address", st.fast_addr)

    async def _on_raylet_message(self, method: str, data, conn):
        if method == "push_task":
            # Actor-creation tasks arrive from the raylet.
            return await self.handle_push_task(data, conn)
        return None

    # -------------------------------------------------------- KV bridge (sync)
    def _kv_put_sync(self, ns: bytes, key: bytes, value: bytes) -> None:
        self._run_on_loop(self.gcs.call("kv_put", {
            "ns": ns, "key": key, "value": value}))

    def _kv_get_sync(self, ns: bytes, key: bytes) -> Optional[bytes]:
        return self._run_on_loop(self.gcs.call("kv_get",
                                               {"ns": ns, "key": key}))

    def _run_on_loop(self, coro, timeout: float = 30.0):
        """Run a coroutine from any thread, including loop callbacks."""
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self.loop:
            raise RuntimeError("_run_on_loop called from the io loop itself")
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    # ---------------------------------------------------------------- put/get
    def _next_task_id(self) -> TaskID:
        return TaskID.of(self.job_id)

    async def put_object(self, value: Any) -> ObjectRef:
        object_id = ObjectID.from_random()
        sobj = ser.serialize(value)
        self.reference_counter.add_owned_object(object_id)
        if sobj.total_size <= self.config.max_direct_call_object_size or \
                self.plasma is None:
            self.memory_store.put_in_loop(object_id, sobj.to_bytes())
        else:
            await self._put_plasma(object_id, sobj)
        return ObjectRef(object_id, owner_address=self.address)

    async def _put_plasma(self, object_id: ObjectID,
                          sobj: ser.SerializedObject) -> None:
        try:
            # Off-loop: the bulk memcpy runs on an executor thread with
            # the GIL dropped (native shm_store_write), so a 100-MiB put
            # doesn't stall the io loop.
            await asyncio.get_running_loop().run_in_executor(
                None, self.plasma.put_serialized, object_id, sobj)
        except StoreFullError:
            # Store the bytes host-side anyway (memory store) rather than fail.
            self.memory_store.put_in_loop(object_id, sobj.to_bytes())
            return
        self.memory_store.mark_in_plasma_in_loop(object_id)
        await self.gcs.call("add_object_location", {
            "object_id": object_id.binary(),
            "node_id": self.node_id.binary() if self.node_id else b"",
        })

    async def get_objects(self, refs: List[ObjectRef],
                          timeout: Optional[float] = None) -> List[Any]:
        deadline = time.monotonic() + timeout if timeout is not None else None
        results = await asyncio.gather(
            *[self._get_one(ref, deadline) for ref in refs])
        out = []
        for value in results:
            if isinstance(value, (ser.RayTaskError, ser.ActorDiedError,
                                  ser.WorkerCrashedError,
                                  ser.TaskCancelledError,
                                  ser.ObjectLostError)):
                raise value
            if isinstance(value, _ObjectLost):
                raise ser.ObjectLostError(value.msg)
            out.append(value)
        return out

    async def _get_one(self, ref: ObjectRef, deadline: Optional[float]) -> Any:
        object_id = ref.id
        while True:
            # 1. memory store (inline/small objects owned or cached here)
            data = self.memory_store.get_if_exists(object_id)
            if data is not None:
                return ser.loads(data)
            # 2. local shm
            if self.plasma is not None:
                buf = self.plasma.get(object_id, timeout_ms=0)
                if buf is not None:
                    # buf.data pins the object for the lifetime of every
                    # view deserialized out of it (PlasmaBuffer protocol).
                    return ser.deserialize(buf.data)
            remaining = _remaining(deadline)
            if remaining is not None and remaining <= 0:
                raise ser.GetTimeoutError(f"get timed out on {ref}")
            if self.reference_counter.is_owned(object_id):
                # 3a. owned & pending: wait for the producing task
                if object_id.task_id() in self._pending_tasks:
                    await self.memory_store.wait_ready(
                        object_id, min(remaining or 1.0, 1.0))
                    continue
                # 3b. owned, was in plasma, local miss: evicted or spilled —
                # restore through the raylet (which also restores spills).
                if self.memory_store.is_in_plasma(object_id) and \
                        self.raylet is not None:
                    r = await self.raylet.call("pull_object", {
                        "object_id": object_id.binary(),
                        "owner_address": self.address,
                        "timeout": 5.0}, timeout=10.0)
                    if r.get("status") == "local":
                        continue
                # 3c. lineage reconstruction: resubmit the producing task
                # (reference: ObjectRecoveryManager::ReconstructObject).
                spec = self.reference_counter.get_lineage(object_id)
                if spec is not None and self.config.lineage_enabled:
                    self.memory_store.delete(object_id)
                    await self._reconstruct(spec)
                    continue
                return _ObjectLost(
                    f"owned object {ref} was lost (no copies, no lineage)")
            # 4. borrowed: ask the owner / pull via raylet
            value = await self._get_remote(ref, deadline)
            if value is not _RETRY:
                return value
            remaining = _remaining(deadline)
            if remaining is not None and remaining <= 0:
                raise ser.GetTimeoutError(f"get timed out on {ref}")
            await asyncio.sleep(0.02)

    async def _get_remote(self, ref: ObjectRef, deadline: Optional[float]):
        owner = ref.owner_address or \
            self.reference_counter.owner_address(ref.id)
        if owner and owner != self.address:
            try:
                conn = await self._peer(owner)
                r = await conn.call("get_object",
                                    {"object_id": ref.id.binary()},
                                    timeout=5.0)
            except Exception:
                return _ObjectLost(f"owner {owner} of {ref} is unreachable")
            if r.get("inline") is not None:
                self.memory_store.put_in_loop(ref.id, r["inline"])
                return ser.loads(r["inline"])
            if r.get("status") == "pending":
                return _RETRY
            if r.get("status") == "lost":
                return _ObjectLost(f"object {ref} was lost: {r.get('error')}")
            # plasma somewhere: fall through to raylet pull
        if self.raylet is not None:
            r = await self.raylet.call("pull_object", {
                "object_id": ref.id.binary(),
                "owner_address": owner,
                "timeout": min(_remaining(deadline) or 30.0, 30.0),
            }, timeout=35.0)
            if r["status"] == "local":
                buf = self.plasma.get(ref.id, timeout_ms=1000)
                if buf is not None:
                    return ser.deserialize(buf.data)
        return _RETRY

    async def wait_objects(self, refs: List[ObjectRef], num_returns: int,
                           timeout: Optional[float],
                           fetch_local: bool) -> Tuple[list, list]:
        deadline = time.monotonic() + timeout if timeout is not None else None
        pending = {ref: asyncio.ensure_future(self._ready(ref, deadline))
                   for ref in refs}
        ready: List[ObjectRef] = []
        try:
            # One scheduling pass so each _ready probe runs its first local
            # availability check even with timeout=0 (ray.wait(timeout=0)
            # must report already-available objects).
            await asyncio.sleep(0)
            ready = [r for r, f in pending.items()
                     if f.done() and not f.cancelled() and f.result()]
            while len(ready) < num_returns:
                remaining = _remaining(deadline)
                if remaining is not None and remaining <= 0:
                    break
                waiting = [f for f in pending.values() if not f.done()]
                if not waiting:
                    break
                await asyncio.wait(waiting, timeout=remaining,
                                   return_when=asyncio.FIRST_COMPLETED)
                ready = [r for r, f in pending.items()
                         if f.done() and not f.cancelled() and f.result()]
        finally:
            for f in pending.values():
                if not f.done():
                    f.cancel()
        ready = ready[:num_returns]
        not_ready = [r for r in refs if r not in ready]
        return ready, not_ready

    async def _ready(self, ref: ObjectRef, deadline: Optional[float]) -> bool:
        while True:
            if self.memory_store.contains(ref.id):
                return True
            if self.plasma is not None and self.plasma.contains(ref.id):
                return True
            if not self.reference_counter.is_owned(ref.id):
                owner = ref.owner_address
                if owner and owner != self.address:
                    try:
                        conn = await self._peer(owner)
                        r = await conn.call(
                            "get_object",
                            {"object_id": ref.id.binary(), "probe": True},
                            timeout=5.0)
                        if r.get("status") in ("ok", "plasma") or \
                                r.get("inline") is not None:
                            return True
                    except Exception:
                        return True  # owner gone: counts as "resolved" (error)
            remaining = _remaining(deadline)
            if remaining is not None and remaining <= 0:
                return False
            ok = await self.memory_store.wait_ready(
                ref.id, min(remaining or 0.25, 0.25) or 0.25)
            if ok:
                return True

    # ------------------------------------------------------------- peers
    async def _peer(self, address: str) -> rpc.Connection:
        conn = self._peer_conns.get(address)
        if conn is None or conn.closed:
            host, port = address.rsplit(":", 1)
            # Peer conns are bidirectional: the remote end may send
            # notifies back over them (e.g. stream_ack / cancel_stream
            # from a streaming consumer to its producer).
            conn = await rpc.connect(host, int(port), name=f"peer:{address}",
                                     handler=self._dispatch_peer,
                                     timeout=5.0)
            self._peer_conns[address] = conn
        return conn

    async def _dispatch_peer(self, method: str, data, conn):
        fn = getattr(self, "handle_" + method, None)
        if fn is None:
            raise rpc.RpcError(f"no handler for {method}")
        return await fn(data, conn)

    # ------------------------------------------------------------- refcount
    def _on_object_out_of_scope(self, object_id: ObjectID) -> None:
        # Only objects that actually reached the shm store need the
        # cluster-wide free; inline results (the overwhelmingly common
        # case) die right here — no per-ref loop hop.
        in_plasma = self.memory_store.is_in_plasma(object_id)
        self.memory_store.delete(object_id)
        self._pending_tasks.pop(object_id.task_id(), None)
        if in_plasma and self.plasma is not None and \
                self.raylet is not None and self.loop.is_running():
            asyncio.run_coroutine_threadsafe(
                self._free_everywhere(object_id), self.loop)

    async def _free_everywhere(self, object_id: ObjectID) -> None:
        try:
            if self.raylet and not self.raylet.closed:
                await self.raylet.call("free_object",
                                       {"object_id": object_id.binary()})
        except Exception:
            pass

    def register_borrow(self, object_id: ObjectID,
                        owner_address: Optional[str]) -> None:
        """Called when a ref owned elsewhere is deserialized here: record the
        borrow and tell the owner so it keeps the object alive
        (reference: ReferenceCounter borrower protocol)."""
        if not owner_address or owner_address == self.address:
            return
        if self.reference_counter.is_owned(object_id):
            return
        self.reference_counter.add_borrowed_object(object_id, owner_address)
        key = (object_id, owner_address)
        if key in self._borrowed_notified:
            return
        self._borrowed_notified.add(key)

        async def go():
            try:
                conn = await self._peer(owner_address)
                await conn.notify("ref_added", {
                    "object_id": object_id.binary(),
                    "borrower": self.address})
            except Exception:
                pass
        if self.loop.is_running():
            asyncio.run_coroutine_threadsafe(go(), self.loop)

    def _notify_owner_ref_removed(self, object_id: ObjectID,
                                  owner_address: str) -> None:
        self._borrowed_notified.discard((object_id, owner_address))

        async def go():
            try:
                conn = await self._peer(owner_address)
                await conn.notify("ref_removed", {
                    "object_id": object_id.binary(),
                    "borrower": self.address})
            except Exception:
                pass
        if self.loop.is_running():
            asyncio.run_coroutine_threadsafe(go(), self.loop)

    async def handle_ref_added(self, data, conn) -> bool:
        self.reference_counter.add_borrower(ObjectID(data["object_id"]),
                                            data["borrower"])
        return True

    async def handle_ref_removed(self, data, conn) -> bool:
        self.reference_counter.remove_borrower(ObjectID(data["object_id"]),
                                               data["borrower"])
        return True

    async def handle_get_object(self, data, conn) -> dict:
        """Owner-side: serve an object to a borrower."""
        object_id = ObjectID(data["object_id"])
        bytes_ = self.memory_store.get_if_exists(object_id)
        if bytes_ is not None:
            if data.get("probe"):
                return {"status": "ok"}
            return {"inline": bytes_}
        if self.memory_store.is_in_plasma(object_id) or \
                (self.plasma and self.plasma.contains(object_id)):
            return {"status": "plasma"}
        if self.reference_counter.is_owned(object_id):
            if object_id.task_id() in self._pending_tasks:
                return {"status": "pending"}
            # Lost (e.g. evicted with no copies): try lineage reconstruction.
            spec = self.reference_counter.get_lineage(object_id)
            if spec is not None and self.config.lineage_enabled:
                asyncio.get_running_loop().create_task(
                    self._reconstruct(spec))
                return {"status": "pending"}
            return {"status": "lost", "error": "no copies and no lineage"}
        return {"status": "lost", "error": "not the owner"}

    async def _reconstruct(self, spec: TaskSpec) -> None:
        """Lineage reconstruction: resubmit the producing task (reference:
        ObjectRecoveryManager::ReconstructObject)."""
        if spec.task_id in self._pending_tasks:
            return
        logger.info("reconstructing via task %s", spec.function.display())
        self._pending_tasks[spec.task_id] = spec
        await self._submit_to_lease(spec)

    # ------------------------------------------------------------- submission
    def submit_task_sync(self, descriptor: FunctionDescriptor,
                         args: tuple, kwargs: dict, opts: dict
                         ) -> List[ObjectRef]:
        """Submit a normal task from ANY thread without waiting for the loop.

        The hot half of the reference's SubmitTask path (spec build, return
        refs, ref bookkeeping — normal_task_submitter.cc:24) runs on the
        caller's thread; only the lease/push pump is posted to the io loop,
        fire-and-forget, so `.remote()` costs no cross-thread round trip.
        Submission failures surface on get() via error-envelope returns.
        """
        spec = self._build_spec(NORMAL_TASK, descriptor, args, kwargs, opts)
        if spec.is_streaming:
            self._streams[spec.task_id] = StreamState()
            out: list = [ObjectRefGenerator(spec.task_id, self)]
        else:
            out = [ObjectRef(oid, owner_address=self.address)
                   for oid in spec.return_ids()]
            for oid in spec.return_ids():
                self.reference_counter.add_owned_object(
                    oid,
                    lineage_task=spec if self.config.lineage_enabled else None)
        self._pending_tasks[spec.task_id] = spec
        # Fastlane: a key in fast mode sends from THIS thread over the
        # native channel — the io loop is not involved per task at all
        # (and one RUNNING event stands in for PENDING+RUNNING).
        fk = self._fast_keys.get(spec.scheduling_key())
        if fk is not None and fk.submit_spec(spec):
            self._record_task_event(spec, "RUNNING")
            return out
        self._record_task_event(spec, "PENDING")
        self.loop.call_soon_threadsafe(self._enqueue_for_lease, spec)
        return out

    async def submit_task(self, descriptor: FunctionDescriptor,
                          args: tuple, kwargs: dict, opts: dict
                          ) -> List[ObjectRef]:
        return self.submit_task_sync(descriptor, args, kwargs, opts)

    def _enqueue_for_lease(self, spec: TaskSpec) -> None:
        key = spec.scheduling_key()
        state = self._scheduling_keys.get(key)
        if state is None:
            state = self._scheduling_keys[key] = _SchedulingKeyState()
        state.queue.append(spec)
        self._pump_scheduling_key(key, state)

    def _build_spec(self, task_type: int,
                    descriptor: FunctionDescriptor, args: tuple,
                    kwargs: dict, opts: dict,
                    actor_id: Optional[ActorID] = None,
                    method: str = "", seqno: int = -1) -> TaskSpec:
        kwarg_keys = sorted(kwargs.keys())
        wire_args = []
        for arg in list(args) + [kwargs[k] for k in kwarg_keys]:
            if isinstance(arg, ObjectRef):
                self.reference_counter.add_submitted_task_ref(arg.id)
                # Dependency inlining (reference: dependency_resolver.cc):
                # owner-local small objects ride inside the spec.
                inline = self.memory_store.get_if_exists(arg.id)
                if inline is not None and \
                        len(inline) <= self.config.max_direct_call_object_size:
                    wire_args.append((ARG_VALUE, inline, None))
                    self.reference_counter.remove_submitted_task_ref(arg.id)
                else:
                    wire_args.append((ARG_REF, arg.id.binary(),
                                      arg.owner_address or self.address))
            else:
                wire_args.append((ARG_VALUE, ser.dumps(arg), None))
        num_returns = opts.get("num_returns", 1)
        if num_returns == "streaming":
            num_returns = STREAMING
        res_memo_key = f"_res_memo_{task_type}"
        resources = opts.get(res_memo_key)
        if resources is None:
            resources = _normalize_resources(opts, task_type)
            try:
                # opts may be the RemoteFunction's cached resolved dict:
                # memoize there so repeat submissions skip the rebuild.
                opts[res_memo_key] = resources
            except TypeError:
                pass
        strategy = opts.get("scheduling_strategy")
        pg_id = None
        bundle = -1
        if isinstance(strategy, dict) and \
                strategy.get("type") == "placement_group":
            from ray_tpu.core.ids import PlacementGroupID

            pg_id = PlacementGroupID(strategy["pg_id"])
            bundle = strategy.get("bundle_index", -1)
        # Trace propagation (reference: tracing_helper.py:326 — span
        # context rides task metadata): a task submitted from INSIDE a
        # task/actor call inherits the caller's trace id with the caller
        # as parent span; a driver-root submission opens a new trace.
        task_id = self._next_task_id()
        parent = self._current_task
        if parent is not None and parent.trace_ctx:
            trace_ctx = {"trace_id": parent.trace_ctx["trace_id"],
                         "parent_span_id": parent.task_id.hex()}
        else:
            trace_ctx = {"trace_id": task_id.hex(), "parent_span_id": ""}
        return TaskSpec(
            task_id=task_id,
            job_id=self.job_id,
            task_type=task_type,
            function=descriptor,
            args=wire_args,
            num_returns=num_returns,
            resources=resources,
            caller_address=self.address,
            scheduling_strategy=strategy if isinstance(strategy, dict) else None,
            placement_group_id=pg_id,
            placement_group_bundle_index=bundle,
            max_retries=opts.get("max_retries", self.config.task_max_retries),
            retry_exceptions=opts.get("retry_exceptions", False),
            actor_id=actor_id,
            actor_method=method,
            actor_seqno=seqno,
            actor_creation_spec=opts.get("actor_creation_spec"),
            runtime_env=opts.get("runtime_env"),
            name=opts.get("name", descriptor.display()),
            kwarg_keys=kwarg_keys,
            trace_ctx=trace_ctx,
        )

    async def _submit_to_lease(self, spec: TaskSpec) -> None:
        self._enqueue_for_lease(spec)

    def _pump_scheduling_key(self, key: tuple,
                             state: _SchedulingKeyState) -> None:
        # Fastlane hand-off: once a key is observed-tiny and a granted
        # lease advertises a fastlane port, move the key into fast mode —
        # the channel owns that lease; queued specs drain into it and new
        # submissions bypass the loop entirely (submit_task_sync).
        if self.config.fastlane_enabled:
            fk = self._fast_keys.get(key)
            if fk is None and state.duration_ema is not None and \
                    state.duration_ema <= \
                    self.config.pipeline_task_duration_s and \
                    self.config.max_tasks_in_flight_per_worker > 1:
                for lease in state.leases:
                    if lease.fast_addr and lease.inflight == 0:
                        fk = self._activate_fast_key(key, state, lease)
                        break
            if fk is not None:
                while state.queue:
                    if not fk.submit_spec(state.queue[0]):
                        fk = None  # channel died mid-drain; loop flow below
                        break
                    state.queue.pop(0)
                if fk is not None and not state.queue:
                    for lease in [l for l in state.leases
                                  if l.inflight == 0]:
                        state.leases.remove(lease)
                        self.loop.create_task(self._return_lease(lease))
                    return
        # Assign queued tasks to leases BREADTH-FIRST: one task per idle
        # lease (strict spread semantics, matching the reference's
        # one-in-flight `lease_entry.is_busy`, normal_task_submitter.cc:197).
        # Tasks this key has OBSERVED to be tiny additionally pipeline up
        # to max_tasks_in_flight_per_worker deep — tiny tasks gain nothing
        # from spread, and pipelining removes the per-task lease round
        # trip that dominates their throughput. Long/unknown-duration
        # tasks never pipeline, so they spread exactly as with depth 1.
        for lease in state.leases:
            if state.queue and lease.inflight == 0:
                self._assign_to_lease(state.queue.pop(0), lease, key, state)
        depth = max(1, self.config.max_tasks_in_flight_per_worker)
        if state.queue and depth > 1 and \
                state.duration_ema is not None and \
                state.duration_ema <= self.config.pipeline_task_duration_s:
            for lease in state.leases:
                while state.queue and lease.inflight < depth:
                    self._assign_to_lease(state.queue.pop(0), lease, key,
                                          state)
        # One lease request per queued task, a few in parallel (reference:
        # NormalTaskSubmitter keeps a pending lease request while tasks are
        # queued) — so multi-node spread is immediate.
        while state.queue and state.requests_inflight < min(
                len(state.queue), self.config.max_pending_lease_requests):
            state.requests_inflight += 1
            spec = state.queue[0]
            self.loop.create_task(
                self._request_lease(spec, key, state))
        # Return leases that arrived after the queue drained (otherwise they
        # pin their resources forever).
        if not state.queue:
            for lease in [l for l in state.leases if l.inflight == 0]:
                state.leases.remove(lease)
                self.loop.create_task(
                    self._return_lease(lease))

    def _assign_to_lease(self, spec: TaskSpec, lease: "_Lease", key: tuple,
                         state: _SchedulingKeyState) -> None:
        lease.inflight += 1
        self.loop.create_task(self._push_task(spec, lease, key, state))

    async def _request_lease(self, spec: TaskSpec, key: tuple,
                             state: _SchedulingKeyState,
                             raylet_address: Optional[str] = None,
                             num_spillbacks: int = 0,
                             lease_attempts: int = 0) -> None:
        lease_id = os.urandom(16)
        try:
            if raylet_address is None and spec.placement_group_id is not None:
                # Bundle-pinned tasks go straight to the bundle's raylet.
                r = await self.gcs.call("get_pg_raylet", {
                    "pg_id": spec.placement_group_id.binary(),
                    "bundle_index": spec.placement_group_bundle_index,
                    "timeout": 60.0,
                }, timeout=65.0)
                if r.get("error"):
                    state.requests_inflight -= 1
                    self._fail_queued(key, state, r["error"])
                    return
                raylet_address = r["address"]
            if raylet_address is None or raylet_address == "local":
                conn = self.raylet
                raylet_address = self.raylet_address
            else:
                conn = await self._peer(raylet_address)
            reply = await conn.call("request_worker_lease", {
                "lease_id": lease_id,
                "resources": spec.resources,
                "pg_id": spec.placement_group_id.binary()
                if spec.placement_group_id else None,
                "pg_bundle": spec.placement_group_bundle_index,
                "job_id": self.job_id.binary(),
                "num_spillbacks": num_spillbacks,
            }, timeout=self.config.worker_lease_timeout_s + 60)
        except Exception as e:
            # A raylet dying mid-lease (e.g. a spillback target) is a
            # transient infrastructure failure, not a task failure: retry
            # via the local raylet, whose refreshed cluster view spills
            # to nodes that are still alive.
            if lease_attempts < 3:
                logger.info(
                    "lease via %s failed (%r); retrying via local raylet "
                    "(attempt %d)", raylet_address, e, lease_attempts + 1)
                await asyncio.sleep(0.2 * (lease_attempts + 1))
                await self._request_lease(
                    spec, key, state, raylet_address=None,
                    num_spillbacks=0, lease_attempts=lease_attempts + 1)
                return
            state.requests_inflight -= 1
            self._fail_queued(key, state, f"lease request failed: {e!r}")
            return
        if reply.get("spillback"):
            await self._request_lease(spec, key, state,
                                      raylet_address=reply["spillback"],
                                      num_spillbacks=num_spillbacks + 1,
                                      lease_attempts=lease_attempts)
            return
        state.requests_inflight -= 1
        if reply.get("error"):
            self._fail_queued(key, state, reply["error"])
            return
        try:
            conn = await self._peer(reply["worker_address"])
        except Exception as e:
            self._fail_queued(key, state, f"worker connect failed: {e}")
            return
        lease = _Lease(lease_id, reply["worker_address"], conn,
                       raylet_address,
                       fast_addr=reply.get("worker_fast_address", ""))
        state.leases.append(lease)
        self._pump_scheduling_key(key, state)

    def _fail_queued(self, key: tuple, state: _SchedulingKeyState,
                     error: str) -> None:
        for spec in state.queue:
            self._store_error_returns(
                spec, ser.RayTaskError(spec.function.display(), error, error))
        state.queue.clear()

    async def _push_task(self, spec: TaskSpec, lease: _Lease, key: tuple,
                         state: _SchedulingKeyState) -> None:
        self._record_task_event(spec, "RUNNING")
        retry_app_error = False
        try:
            reply = await lease.conn.call("push_task",
                                          {"task": spec.to_wire()})
            exec_s = reply.get("exec_s")
            if exec_s is not None:
                state.duration_ema = (exec_s if state.duration_ema is None
                                      else 0.7 * state.duration_ema +
                                      0.3 * exec_s)
            # Application-level retry (reference: TaskManager retries with
            # retry_exceptions=True).
            if reply.get("status") == "error" and spec.retry_exceptions and \
                    spec.max_retries > 0:
                spec.max_retries -= 1
                retry_app_error = True
            else:
                self._handle_task_reply(spec, reply)
        except Exception as e:
            # Worker crashed mid-task: retry or fail (reference:
            # TaskManager retries).
            if lease in state.leases:
                state.leases.remove(lease)
            await self._return_lease(lease, disconnect=True)
            if spec.max_retries > 0:
                spec.max_retries -= 1
                logger.info("retrying task %s after worker failure (%s)",
                            spec.name, e)
                await self._submit_to_lease(spec)
            else:
                self._store_error_returns(spec, ser.RayTaskError(
                    spec.function.display(),
                    f"worker at {lease.address} died: {e}",
                    "WorkerCrashedError"))
            return
        lease.inflight -= 1
        if not retry_app_error:
            self._release_task_arg_refs(spec)
        if state.queue:
            self._pump_scheduling_key(key, state)
        elif lease.inflight == 0 and not retry_app_error:
            # No more work for this key: give the worker back.
            if lease in state.leases:
                state.leases.remove(lease)
            await self._return_lease(lease)
        if retry_app_error:
            logger.info("retrying task %s after application error (%d left)",
                        spec.name, spec.max_retries)
            await self._submit_to_lease(spec)

    async def _return_lease(self, lease: _Lease,
                            disconnect: bool = False) -> None:
        try:
            if lease.raylet_address == self.raylet_address:
                conn = self.raylet
            else:
                conn = await self._peer(lease.raylet_address)
            await conn.call("return_worker", {
                "lease_id": lease.lease_id, "disconnect": disconnect})
        except Exception:
            pass

    def _handle_task_reply(self, spec: TaskSpec, reply: dict) -> None:
        self._pending_tasks.pop(spec.task_id, None)
        self._record_task_event(
            spec, "FINISHED" if reply.get("status") == "ok" else "FAILED")
        if spec.is_streaming:
            self._finish_stream(spec.task_id,
                                reply.get("stream_total", 0),
                                reply.get("stream_error"))
            return
        for oid_b, inline in reply.get("returns", []):
            oid = ObjectID(oid_b)
            if inline is None:
                self.memory_store.mark_in_plasma_in_loop(oid)
            else:
                self.memory_store.put_in_loop(oid, inline)
            self._reap_if_unreferenced(oid, inline is None)

    def _reap_if_unreferenced(self, oid: ObjectID, in_plasma: bool) -> None:
        """A result landing for a ref that already went out of scope must
        not leak: out-of-scope skipped the cluster free (no marker yet /
        nothing stored), so the reply side finishes the job. Safe under
        any interleaving with ObjectRef.__del__: whichever of the two
        observes the other's write performs the free."""
        if self.reference_counter.is_owned(oid):
            return
        self.memory_store.delete(oid)
        if in_plasma and self.raylet is not None and self.loop.is_running():
            asyncio.run_coroutine_threadsafe(
                self._free_everywhere(oid), self.loop)

    def _release_task_arg_refs(self, spec: TaskSpec) -> None:
        for kind, payload, _ in spec.args:
            if kind == ARG_REF:
                self.reference_counter.remove_submitted_task_ref(
                    ObjectID(payload))

    def _store_error_returns(self, spec: TaskSpec, error: Exception) -> None:
        self._pending_tasks.pop(spec.task_id, None)
        self._record_task_event(spec, "FAILED")
        blob = ser.dumps(error)
        if spec.is_streaming:
            st = self._streams.get(spec.task_id)
            self._finish_stream(
                spec.task_id,
                max(st.received) + 1 if st and st.received else 0, blob)
        for oid in spec.return_ids():
            self.memory_store.put_in_loop(oid, blob)
        self._release_task_arg_refs(spec)

    # ------------------------------------------------- fastlane (submitter)
    def _activate_fast_key(self, key: tuple, state: _SchedulingKeyState,
                           lease: _Lease) -> Optional[_FastKey]:
        from ray_tpu.core.fastlane import FastChannel

        cell: list = []  # lets on_close identify WHICH channel died
        try:
            ch = FastChannel(
                lease.fast_addr, self._fastlane_on_reply,
                lambda pend, k=key, c=cell:
                    self._fastlane_key_closed(k, pend,
                                              c[0] if c else None))
        except Exception:
            lease.fast_addr = ""  # don't retry this lease
            return None
        cell.append(ch)
        state.leases.remove(lease)
        fk = _FastKey(key, ch, lease)
        self._fast_keys[key] = fk
        if ch.dead:
            # Died between connect and install: on_close ran before the
            # fk existed and couldn't reap it — do it now (returns the
            # lease, unwedges the key).
            self._fastlane_key_closed(key, [], ch)
            return None
        return fk

    def _fastlane_on_reply(self, ctx, reply: dict) -> None:
        """Channel pump thread: one task completed on the fast path."""
        kind, spec, extra = ctx
        if reply.get("status") == "error" and spec.retry_exceptions and \
                spec.max_retries > 0:
            spec.max_retries -= 1
            if kind == "actor":
                self._queue_actor_push(spec, extra)
            else:
                self.loop.call_soon_threadsafe(self._enqueue_for_lease, spec)
            return
        self._handle_task_reply_sync(spec, reply)
        self._release_task_arg_refs(spec)
        if kind == "task":
            key = extra
            state = self._scheduling_keys.get(key)
            exec_s = reply.get("exec_s")
            if state is not None and exec_s is not None:
                state.duration_ema = (
                    exec_s if state.duration_ema is None
                    else 0.7 * state.duration_ema + 0.3 * exec_s)
            fk = self._fast_keys.get(key)
            if fk is not None and fk.channel.pending_count() == 0 and \
                    not fk.deact_scheduled:
                # Idle: linger briefly (bursty submitters reuse the
                # channel), then give the lease back. The flag keeps a
                # worker-keeps-pace burst (pending bouncing 0<->1) from
                # waking the loop once per task.
                fk.deact_scheduled = True
                self.loop.call_soon_threadsafe(
                    lambda: self.loop.call_later(
                        0.25, self._maybe_deactivate_fast_key, key))

    def _maybe_deactivate_fast_key(self, key: tuple) -> None:
        fk = self._fast_keys.get(key)
        if fk is None or fk.channel.dead:
            return
        fk.deact_scheduled = False
        if fk.channel.pending_count() > 0:
            return
        state = self._scheduling_keys.get(key)
        if state is not None and state.queue:
            return
        del self._fast_keys[key]

        def finish():
            if fk.channel.dead:
                # Died after deactivation removed it from the dict, so
                # on_close could not reap it — the lease is ours to return.
                self.loop.create_task(
                    self._return_lease(fk.lease, disconnect=True))
                return
            if fk.channel.pending_count() == 0:
                fk.channel.close()
                self.loop.create_task(self._return_lease(fk.lease))
            elif key not in self._fast_keys:
                # A submitter holding a stale reference slipped one in:
                # reinstate and retry later.
                self._fast_keys[key] = fk
            else:
                # A NEW fast key already took the slot: let this one's
                # stragglers drain, then retire it.
                self.loop.call_later(0.25, finish)

        self.loop.call_later(0.05, finish)

    def _fastlane_key_closed(self, key: tuple, pending: list,
                             channel=None) -> None:
        """Channel pump thread, connection lost: resubmit outstanding work
        through the loop path with normal retry semantics. Pops the fast
        key only if it still owns THIS channel — a deactivated old
        channel's close must not reap a re-activated successor."""
        fk = self._fast_keys.get(key)
        if fk is not None and (channel is None or fk.channel is channel):
            self._fast_keys.pop(key, None)
        else:
            fk = None  # not ours to reap; finish()/successor owns cleanup

        graceful = bool(channel is not None and
                        getattr(channel, "graceful_close", False))

        def go():
            if fk is not None:
                self.loop.create_task(
                    self._return_lease(fk.lease, disconnect=not graceful))
            for _kind, spec, _extra in pending:
                if graceful:
                    # Deactivation raced a straggler submission: the
                    # worker is fine — resubmit without burning a retry.
                    self._enqueue_for_lease(spec)
                elif spec.max_retries > 0:
                    spec.max_retries -= 1
                    self._enqueue_for_lease(spec)
                else:
                    self._store_error_returns(spec, ser.RayTaskError(
                        spec.function.display(),
                        "worker died (fastlane connection lost)",
                        "WorkerCrashedError"))

        self.loop.call_soon_threadsafe(go)

    def _handle_task_reply_sync(self, spec: TaskSpec, reply: dict) -> None:
        """Thread-safe twin of _handle_task_reply (channel pump threads):
        results land via MemoryStore.put_sync, and returns are stored
        BEFORE the pending entry is popped so a concurrent _get_fast
        recheck can't conclude 'lost' mid-processing."""
        ok = reply.get("status") == "ok"
        if spec.is_streaming:
            self._pending_tasks.pop(spec.task_id, None)
            self._record_task_event(spec, "FINISHED" if ok else "FAILED")
            self._finish_stream(spec.task_id,
                                reply.get("stream_total", 0),
                                reply.get("stream_error"))
            return
        returns = reply.get("returns", [])
        if not ok and not returns:
            # Transport-level failure (e.g. the dispatcher could not even
            # parse the spec): synthesize error envelopes so gets resolve.
            blob = ser.dumps(ser.RayTaskError(
                spec.name, reply.get("error", "task failed"),
                reply.get("error", "task failed")))
            returns = [[oid.binary(), blob] for oid in spec.return_ids()]
        for oid_b, inline in returns:
            oid = ObjectID(oid_b)
            if inline is None:
                self.memory_store.mark_in_plasma_sync(oid)
            else:
                self.memory_store.put_sync(oid, inline)
            self._reap_if_unreferenced(oid, inline is None)
        self._pending_tasks.pop(spec.task_id, None)
        self._record_task_event(spec, "FINISHED" if ok else "FAILED")

    # ------------------------------------------------- streaming generators
    async def handle_stream_item(self, data, conn) -> bool:
        """Caller-side: one yielded value reported by the executing worker
        (reference: the streaming-generator return protocol around
        python/ray/_raylet.pyx:277)."""
        self._accept_stream_item(data, conn)
        return True

    def _accept_stream_item(self, item: dict, conn=None) -> None:
        task_id = TaskID(item["task_id"])
        st = self._streams.get(task_id)
        if st is None or getattr(st, "released", False):
            # Unknown or abandoned stream: tell the producer to stop and
            # flush its backpressure window so it can't stall forever.
            if conn is not None:
                self._loop_notify(conn, "cancel_stream",
                                  {"task_id": item["task_id"]})
                self._loop_notify(conn, "stream_ack", {
                    "task_id": item["task_id"], "consumed": 1 << 62})
            return
        if conn is not None:
            st.producer_conn = conn  # ack/cancel channel back to producer
        index = item["index"]
        with st.cond:
            if index in st.received:
                # Duplicate (task retry re-ran the generator): re-ack the
                # consumer's cursor so the FRESH producer's backpressure
                # window reflects what was already consumed — otherwise a
                # retry after >=bp_limit consumed items deadlocks.
                if conn is not None:
                    self._loop_notify(conn, "stream_ack", {
                        "task_id": item["task_id"],
                        "consumed": st.next_index})
                return
            oid = ObjectID.for_task_return(task_id, index)
            self.reference_counter.add_owned_object(oid)
            if item.get("data") is not None:
                self.memory_store.put_in_loop(oid, item["data"])
            else:
                self.memory_store.mark_in_plasma(oid)
            st.received.add(index)
            # The ref is created here (loop thread) so the stream holds a
            # live local ref until the consumer takes it or releases the
            # generator.
            st.ready[index] = ObjectRef(oid, owner_address=self.address)
            st.cond.notify_all()

    def _loop_notify(self, conn, method: str, data: dict) -> None:
        """Fire-and-forget notify from the loop thread."""

        async def go():
            try:
                await conn.notify(method, data)
            except Exception:
                pass

        self.loop.create_task(go())

    def _finish_stream(self, task_id: TaskID, total: int,
                       error_blob: Optional[bytes]) -> None:
        st = self._streams.get(task_id)
        if st is None:
            return
        with st.cond:
            st.total = total
            if error_blob is not None:
                st.error_blob = error_blob
            st.cond.notify_all()
        if getattr(st, "released", False):
            # Abandoned stream's task finished: reap the state now.
            self._streams.pop(task_id, None)

    def stream_next(self, task_id: TaskID, timeout: Optional[float] = None):
        """Blocking next-ref for ObjectRefGenerator (any thread)."""
        st = self._streams.get(task_id)
        if st is None:
            raise StopIteration
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        with st.cond:
            while True:
                i = st.next_index
                if i in st.received:
                    st.next_index += 1
                    ref = st.ready.pop(i)
                    self._send_stream_ack(st, task_id, i + 1)
                    return ref
                if st.total is not None and i >= st.total:
                    if st.error_blob is not None and not st.error_raised:
                        st.error_raised = True
                        raise ser.loads(st.error_blob)
                    raise StopIteration
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise ser.GetTimeoutError(
                        f"stream item {i} of task {task_id.hex()[:8]} not "
                        f"ready within {timeout}s")
                st.cond.wait(min(remaining, 1.0) if remaining else 1.0)

    def stream_completed(self, task_id: TaskID) -> bool:
        st = self._streams.get(task_id)
        if st is None:
            return True
        with st.cond:
            return st.total is not None and st.next_index >= st.total and \
                not (st.error_blob and not st.error_raised)

    def _send_stream_ack(self, st: StreamState, task_id: TaskID,
                         consumed: int) -> None:
        """Fire-and-forget consumer-progress report to the producer — it
        advances the producer-side backpressure window."""
        conn = getattr(st, "producer_conn", None)
        payload = {"task_id": task_id.binary(), "consumed": consumed}
        if conn is None or conn.closed:
            # Local produce loop (producer == consumer process).
            self.loop.call_soon_threadsafe(
                self._note_stream_ack, task_id, consumed)
            return

        async def go():
            try:
                await conn.notify("stream_ack", payload)
            except Exception:
                pass

        if self.loop.is_running():
            asyncio.run_coroutine_threadsafe(go(), self.loop)

    def _note_stream_ack(self, task_id: TaskID, consumed: int) -> None:
        if task_id not in self._stream_producing:
            return  # late ack for a finished stream: don't grow state
        if consumed > self._stream_acked.get(task_id, 0):
            self._stream_acked[task_id] = consumed
        ev = self._stream_ack_events.get(task_id)
        if ev is not None:
            ev.set()

    async def handle_stream_ack(self, data, conn) -> bool:
        """Producer-side: consumer progressed; open the backpressure
        window."""
        self._note_stream_ack(TaskID(data["task_id"]), data["consumed"])
        return True

    def release_stream(self, task_id: TaskID) -> None:
        """Drop a generator's unconsumed item refs and tell the producer
        to stop + flush its backpressure window (via cancel_stream_sync,
        which routes over the producer conn or the actor connection). If
        neither channel exists yet (normal task, no item landed), the
        state stays marked `released` so the FIRST item report triggers
        the cancel-back, and _finish_stream reaps it."""
        st = self._streams.get(task_id)
        if st is None:
            return
        with st.cond:
            st.released = True
            st.ready.clear()  # ObjectRef __del__ drops the local refs
            st.cond.notify_all()
        if st.total is not None:
            self._streams.pop(task_id, None)  # already finished: reap now
        else:
            self.cancel_stream_sync(task_id)

    def cancel_stream_sync(self, task_id: TaskID) -> None:
        """Caller-side: ask the producer to stop yielding (cooperative).
        Reference: ray.cancel on a streaming generator task. Routed over
        the producer's item-report connection when one exists (any task
        type), else the actor connection (stream not started yet)."""
        st = self._streams.get(task_id)
        if st is None:
            return
        producer_conn = getattr(st, "producer_conn", None)
        actor_id = getattr(st, "actor_id", None)
        payload = {"task_id": task_id.binary()}

        async def go():
            try:
                conn = producer_conn
                if conn is None or conn.closed:
                    if actor_id is None:
                        return
                    conn = await self._actor_connection(actor_id)
                await conn.notify("cancel_stream", payload)
                await conn.notify("stream_ack", {
                    "task_id": task_id.binary(), "consumed": 1 << 62})
            except Exception:
                pass

        if self.loop.is_running():
            asyncio.run_coroutine_threadsafe(go(), self.loop)

    async def handle_cancel_stream(self, data, conn) -> bool:
        """Executor-side: mark a streaming task as cancelled; its produce
        loop stops at the next yield boundary. Recorded even before the
        task starts producing (a pre-start cancel must not be lost); the
        produce loop's finally clears it, and the set is pruned of
        never-ran entries if it ever grows large."""
        task_id = TaskID(data["task_id"])
        self._stream_cancels.add(task_id)
        ev = self._stream_ack_events.get(task_id)
        if ev is not None:
            ev.set()  # wake a backpressure wait so cancel is seen now
        if len(self._stream_cancels) > 4096:
            self._stream_cancels = {
                t for t in self._stream_cancels
                if t in self._stream_producing}
        return True

    # ------------------------------------------------------------- actors
    def _actor_register_payload(self, descriptor: FunctionDescriptor,
                                args: tuple, kwargs: dict,
                                opts: dict) -> tuple:
        actor_id = ActorID.of(self.job_id)
        creation_opts = dict(opts)
        creation_opts["actor_creation_spec"] = {
            "max_concurrency": opts.get("max_concurrency", 1),
            "max_restarts": opts.get("max_restarts", 0),
        }
        spec = self._build_spec(ACTOR_CREATION_TASK, descriptor, args,
                                kwargs, creation_opts, actor_id=actor_id)
        return actor_id, {
            "actor_id": actor_id.binary(),
            "job_id": self.job_id.binary(),
            "name": opts.get("name") or "",
            "namespace": opts.get("namespace") or "default",
            "class_name": descriptor.display(),
            "max_restarts": opts.get("max_restarts", 0),
            "max_concurrency": opts.get("max_concurrency", 1),
            "detached": bool(opts.get("lifetime") == "detached"),
            "creation_task": spec.to_wire(),
        }

    async def create_actor(self, descriptor: FunctionDescriptor, args: tuple,
                           kwargs: dict, opts: dict) -> ActorID:
        """Synchronous-registration path (named/detached actors: name
        conflicts must raise at .remote() time, reference semantics)."""
        actor_id, payload = self._actor_register_payload(
            descriptor, args, kwargs, opts)
        r = await self.gcs.call("register_actor", payload)
        if not r.get("ok"):
            raise ValueError(r.get("error", "actor registration failed"))
        st = self._actors.setdefault(actor_id, _ActorState())
        st.max_concurrency = opts.get("max_concurrency", 1)
        return actor_id

    def create_actor_sync(self, descriptor: FunctionDescriptor, args: tuple,
                          kwargs: dict, opts: dict) -> ActorID:
        """Caller-thread actor creation for ANONYMOUS actors: id
        assignment + spec build here, GCS registration fired on the loop
        WITHOUT waiting for the ack (reference: actor registration is
        asynchronous in the C++ core worker's creation pipeline —
        gcs_actor_manager.cc processes registrations off the caller's
        critical path). The first connection to the actor awaits the ack
        via st.register_done, so registration failures surface on first
        use. Under a creation storm this removes one GCS round trip per
        actor from the driver's submit loop (~20 ms each on a contended
        host: 32-actor storm submit 724 ms → ~30 ms)."""
        actor_id, payload = self._actor_register_payload(
            descriptor, args, kwargs, opts)
        st = self._actors.setdefault(actor_id, _ActorState())
        st.max_concurrency = opts.get("max_concurrency", 1)
        # Created on the caller thread BEFORE the handle escapes: the
        # first _actor_connection must find the event (wait_actor_alive
        # answers None for not-yet-registered actors). Safe off-loop in
        # 3.10+: asyncio.Event binds to a loop only on first await.
        st.register_done = asyncio.Event()
        self.loop.call_soon_threadsafe(
            lambda: self.loop.create_task(
                self._register_actor_bg(actor_id, payload)))
        return actor_id

    async def _register_actor_bg(self, actor_id: ActorID,
                                 payload: dict) -> None:
        st = self._actors[actor_id]
        try:
            r = await self.gcs.call("register_actor", payload)
            if not r.get("ok"):
                st.register_error = ValueError(
                    r.get("error", "actor registration failed"))
        except asyncio.CancelledError:
            # Loop teardown racing a late create: store a plain error
            # (CancelledError must not later escape unrelated tasks via
            # _actor_connection) and let the cancellation propagate.
            st.register_error = RuntimeError(
                "actor registration cancelled (shutdown)")
            st.register_done.set()
            raise
        except Exception as e:
            st.register_error = e
        st.register_done.set()

    async def _actor_connection(self, actor_id: ActorID) -> rpc.Connection:
        st = self._actors.get(actor_id)
        if st is None:
            st = self._actors[actor_id] = _ActorState()
        async with st.lock:
            if st.conn is not None and not st.conn.closed and \
                    st.state == "ALIVE":
                return st.conn
            if st.register_done is not None:
                # Fire-and-forget registration (create_actor_sync): the
                # GCS ack must land before wait_actor_alive means
                # anything; registration failures surface here.
                await st.register_done.wait()
                if st.register_error is not None:
                    raise st.register_error
            # maybe_pending: a handle this worker did NOT register
            # (deserialized from another process) can race the
            # creator's fire-and-forget registration — ask the GCS for
            # a short existence grace. Locally registered handles just
            # awaited the ack above, so unknown means nonexistent.
            view = await self.gcs.call("wait_actor_alive", {
                "actor_id": actor_id.binary(), "timeout": 60.0,
                "maybe_pending": st.register_done is None}, timeout=65.0)
            if view is None:
                raise ser.ActorDiedError(f"actor {actor_id} does not exist")
            st.state = view["state"]
            st.death_cause = view.get("death_cause", "")
            st.max_concurrency = view.get("max_concurrency",
                                          st.max_concurrency)
            if view["state"] != "ALIVE":
                raise ser.ActorDiedError(
                    f"actor {actor_id.hex()[:8]} is {view['state']}: "
                    f"{st.death_cause}")
            st.address = view["address"]
            st.fast_addr = view.get("fast_address", "")
            host, port = st.address.rsplit(":", 1)
            st.conn = await rpc.connect(host, int(port),
                                        name=f"actor:{actor_id.hex()[:8]}")
            return st.conn

    def submit_actor_task_sync(self, actor_id: ActorID, method: str,
                               args: tuple, kwargs: dict,
                               opts: dict) -> List[ObjectRef]:
        """Submit an actor task from ANY thread without a loop round trip.

        Spec build + ref bookkeeping on the caller's thread; the push task
        is posted fire-and-forget. call_soon_threadsafe callbacks run FIFO,
        so seqno order is preserved on the wire (reference:
        ActorTaskSubmitter's ordered queues).
        """
        opts = dict(opts)
        opts.setdefault("num_returns", 1)
        st = self._actors.setdefault(actor_id, _ActorState())
        with st.seq_lock:
            st.seqno += 1
            seqno = st.seqno
        spec = self._build_spec(ACTOR_TASK, _actor_method_descriptor(
            method), args, kwargs, opts, actor_id=actor_id, method=method,
            seqno=seqno)
        spec.resources = {}
        if spec.is_streaming:
            stream = self._streams[spec.task_id] = StreamState()
            stream.actor_id = actor_id  # enables cooperative stream cancel
            out: list = [ObjectRefGenerator(spec.task_id, self)]
        else:
            out = [ObjectRef(oid, owner_address=self.address)
                   for oid in spec.return_ids()]
            for oid in spec.return_ids():
                self.reference_counter.add_owned_object(oid)
        self._pending_tasks[spec.task_id] = spec
        if self._try_fastlane_actor(st, actor_id, spec):
            return out
        self._queue_actor_push(spec, actor_id)
        return out

    def _try_fastlane_actor(self, st: _ActorState, actor_id: ActorID,
                            spec: TaskSpec) -> bool:
        """Route an actor task over the native channel when safe: the
        sync round trip then costs two process hops and zero io-loop
        wakeups. Engages only once the asyncio path has fully drained
        (loop_inflight == 0) so per-caller order survives the switch."""
        if not self.config.fastlane_enabled or st.fast_disabled:
            return False
        if spec.actor_method == "__dag_loop__":
            # DAG actors are driven by compiled channels; pin everything
            # to the loop path so the long-lived loop call can't gate the
            # fastlane connection.
            st.fast_disabled = True
            return False
        if st.max_concurrency != 1:
            st.fast_disabled = True
            return False
        ch = st.channel
        if ch is None or ch.dead:
            if st.state != "ALIVE" or not st.fast_addr or \
                    st.loop_inflight > 0:
                return False
            from ray_tpu.core.fastlane import FastChannel

            with st.seq_lock:  # one connector
                ch = st.channel
                if ch is None or ch.dead:
                    try:
                        ch = st.channel = FastChannel(
                            st.fast_addr, self._fastlane_on_reply,
                            lambda pend, aid=actor_id:
                                self._fastlane_actor_closed(aid, pend))
                    except Exception:
                        return False
        if st.loop_inflight > 0:
            return False
        return ch.submit(
            msgpack.packb({"task": spec.to_wire()}, use_bin_type=True),
            ("actor", spec, actor_id))

    def _fastlane_actor_closed(self, actor_id: ActorID,
                               pending: list) -> None:
        """Channel pump thread: actor connection lost — push outstanding
        calls through the asyncio path (which owns reconnect/death
        semantics), in submission order."""
        st = self._actors.get(actor_id)
        if st is not None:
            st.channel = None
        for _kind, spec, _extra in pending:
            self._queue_actor_push(spec, actor_id)

    def _queue_actor_push(self, spec: TaskSpec, actor_id: ActorID) -> None:
        """Submit an actor task on the asyncio path (any thread)."""
        st = self._actors.setdefault(actor_id, _ActorState())
        with st.seq_lock:
            st.loop_inflight += 1
        self.loop.call_soon_threadsafe(self._spawn_actor_push, spec,
                                       actor_id)

    def _spawn_actor_push(self, spec: TaskSpec, actor_id: ActorID) -> None:
        task = self.loop.create_task(self._push_actor_task(spec, actor_id))
        st = self._actors.get(actor_id)
        if st is not None:
            def _done(_t, st=st):
                with st.seq_lock:
                    st.loop_inflight -= 1
            task.add_done_callback(_done)

    async def submit_actor_task(self, actor_id: ActorID, method: str,
                                args: tuple, kwargs: dict,
                                opts: dict) -> List[ObjectRef]:
        return self.submit_actor_task_sync(actor_id, method, args, kwargs,
                                           opts)

    async def _push_actor_task(self, spec: TaskSpec, actor_id: ActorID,
                               retry: int = 1) -> None:
        try:
            conn = await self._actor_connection(actor_id)
            reply = await conn.call("push_task", {"task": spec.to_wire()})
            self._handle_task_reply(spec, reply)
            self._release_task_arg_refs(spec)
        except ser.ActorDiedError as e:
            self._store_error_returns(spec, e)
        except Exception as e:
            st = self._actors.get(actor_id)
            if st and st.conn and st.conn.closed:
                st.conn = None
                st.state = "UNKNOWN"
            if retry > 0:
                await asyncio.sleep(0.1)
                await self._push_actor_task(spec, actor_id, retry - 1)
            else:
                self._store_error_returns(spec, ser.ActorDiedError(
                    f"actor task {spec.actor_method} failed: {e}"))

    async def cancel_task(self, ref: ObjectRef) -> bool:
        """Best-effort cancel: drops the task if still queued locally (not
        yet pushed to a worker). Running tasks are not interrupted.
        Reference: CoreWorker::CancelTask (non-force path)."""
        task_id = ref.id.task_id()
        for state in self._scheduling_keys.values():
            for spec in list(state.queue):
                if spec.task_id == task_id:
                    state.queue.remove(spec)
                    self._store_error_returns(spec, ser.TaskCancelledError(
                        f"task {spec.name} was cancelled"))
                    return True
        return False

    async def kill_actor(self, actor_id: ActorID,
                         no_restart: bool = True) -> None:
        st = self._actors.get(actor_id)
        if st is not None and st.register_done is not None:
            # Pipelined registration may not have landed yet; killing
            # before the GCS knows the actor would silently no-op and
            # leak the actor when registration lands moments later.
            await st.register_done.wait()
            if st.register_error is not None:
                # Registration never happened: nothing to kill, and a
                # GCS call would only park a garbage tombstone. Surface
                # the real failure instead of a silent no-op.
                raise st.register_error
        await self.gcs.call("kill_actor", {
            "actor_id": actor_id.binary(), "no_restart": no_restart})

    # ------------------------------------------------------------- execution
    async def handle_push_task(self, data, conn) -> dict:
        spec = TaskSpec.from_wire(data["task"])
        if spec.task_type == ACTOR_TASK:
            return await self._execute_actor_task(spec)
        if spec.task_type == ACTOR_CREATION_TASK:
            return await self._execute_actor_creation(spec)
        return await self._execute_normal_task(spec)

    # ------------------------------------------------- fastlane (executor)
    def _fastlane_dispatch_loop(self) -> None:
        """Native-transport request pump (runs on a plain thread).

        The C++ server (fastlane.cpp) owns accept/read/framing and
        delivers at most one outstanding request per connection; this
        loop executes simple tasks directly — no asyncio involvement —
        and falls back to the loop path for everything else, preserving
        per-caller FIFO order either way (the fallback blocks this
        connection's gate until it completes)."""
        from ray_tpu.core.fastlane import CLOSED

        srv = self._fl_server
        while not self._should_exit.is_set():
            item = srv.next(500)
            if item is None:
                continue
            if item is CLOSED:
                return
            reqid, payload = item
            try:
                reply = self._fastlane_handle(reqid, payload)
                if reply is None:
                    continue  # deferred: a loop-path future replies later
                out = msgpack.packb(reply, use_bin_type=True)
            except Exception as e:
                logger.exception("fastlane dispatch failed")
                out = msgpack.packb(
                    {"status": "error",
                     "error": f"{type(e).__name__}: {e}", "returns": []},
                    use_bin_type=True)
            srv.reply(reqid, out)

    def _fastlane_handle(self, reqid: int, payload: bytes) -> Optional[dict]:
        data = msgpack.unpackb(payload, raw=False)
        if "tasks" in data:
            # Batched submission: execute in order (same FIFO contract as
            # one-frame-per-task), reply once. Fallbacks inside a batch
            # block this dispatcher (order must hold within the batch);
            # batches come from observed-tiny task keys, so that's rare
            # and bounded by the batch size.
            return {"replies": [self._fastlane_handle_one(w)
                                for w in data["tasks"]]}
        spec = TaskSpec.from_wire(data["task"])
        reply = self._try_execute_direct(spec)
        if reply is not None:
            return reply
        # Not direct-eligible (streaming / async / ref args / env /
        # concurrency>1): run the full loop path and reply from its
        # completion callback — a minutes-long task must not park this
        # dispatcher thread and starve other connections. The per-conn
        # FIFO gate still holds: the native server withholds this
        # connection's next request until the deferred reply lands.
        fut = asyncio.run_coroutine_threadsafe(
            self.handle_push_task(data, None), self.loop)
        srv = self._fl_server

        def _relay(f, reqid=reqid):
            try:
                out = msgpack.packb(f.result(), use_bin_type=True)
            except Exception as e:
                out = msgpack.packb(
                    {"status": "error",
                     "error": f"{type(e).__name__}: {e}", "returns": []},
                    use_bin_type=True)
            try:
                srv.reply(reqid, out)
            except Exception:
                logger.exception("fastlane deferred reply failed")

        fut.add_done_callback(_relay)
        return None

    def _fastlane_handle_one(self, wire: dict) -> dict:
        spec = TaskSpec.from_wire(wire)
        reply = self._try_execute_direct(spec)
        if reply is None:
            fut = asyncio.run_coroutine_threadsafe(
                self.handle_push_task({"task": wire}, None), self.loop)
            reply = fut.result()
        return reply

    def _try_execute_direct(self, spec: TaskSpec) -> Optional[dict]:
        """Execute entirely on the dispatcher thread when safe; None means
        'fall back to the loop path' (nothing has run yet)."""
        if spec.is_streaming or spec.runtime_env:
            return None
        for kind, _p, _o in spec.args:
            if kind != ARG_VALUE:
                return None
        if spec.task_type == ACTOR_TASK:
            actor = self._local_actor
            if actor is None or actor.max_concurrency != 1:
                return None
            if spec.actor_method == "__dag_loop__":
                return None
            if self._fl_actor_simple is None:
                self._fl_actor_simple = _all_methods_plain(actor.instance)
            if not self._fl_actor_simple:
                # Actors with async/generator methods keep the loop path:
                # the semaphore there is the concurrency authority.
                return None
            fn = getattr(actor.instance, spec.actor_method, None)
            if fn is None:
                return None
            is_actor = True
        elif spec.task_type == NORMAL_TASK:
            if self._env_seen:
                return None
            fn = self.function_manager.get_cached(spec.function)
            if fn is None:
                blob = self._sync_gcs_call(
                    "kv_get", {"ns": b"fn", "key": spec.function.function_key})
                fn = self.function_manager.load(spec.function, blob)
            key = spec.function.function_key or \
                (spec.function.module, spec.function.qualname)
            iscoro = self._fl_coro_cache.get(key)
            if iscoro is None:
                import inspect

                iscoro = self._fl_coro_cache[key] = (
                    asyncio.iscoroutinefunction(fn) or
                    inspect.isgeneratorfunction(fn) or
                    inspect.isasyncgenfunction(fn))
            if iscoro:
                return None
            is_actor = False
        else:
            return None
        # Publish-then-recheck (Dekker with the GIL): a runtime_env task
        # arriving on the loop sets _env_seen, then waits for
        # _direct_inflight to reach zero before mutating process state.
        with self._direct_lock:
            self._direct_inflight += 1
        if not is_actor and self._env_seen:
            with self._direct_lock:
                self._direct_inflight -= 1
            return None
        t0 = time.monotonic()
        try:
            try:
                args, kwargs = self._resolve_args_sync(spec)
                with self._exec_mutex:
                    prev = self._current_task
                    self._current_task = spec
                    try:
                        result = fn(*args, **kwargs)
                    finally:
                        self._current_task = prev
            except Exception as e:
                return self._store_exception_sync(spec, e)
            try:
                reply = self._store_returns_sync(spec, result)
            except Exception as e:
                # Unpicklable return / arity mismatch must fail THIS task
                # only — escaping here would poison the whole batch.
                return self._store_exception_sync(spec, e)
            reply["exec_s"] = time.monotonic() - t0
            return reply
        finally:
            with self._direct_lock:
                self._direct_inflight -= 1
            if getattr(self, "_gate_env_waiting", 0):
                self.loop.call_soon_threadsafe(self._gate_kick)

    def _gate_kick(self) -> None:
        if hasattr(self, "_gate_cond"):
            self.loop.create_task(self._gate_notify())

    async def _gate_notify(self) -> None:
        async with self._gate_cond:
            self._gate_cond.notify_all()

    def flush_fast_channels(self) -> None:
        """Push any batched fastlane submissions to the wire; called on
        the blocking API entry points (get/wait) so batching never delays
        a result the caller is already waiting for."""
        for fk in list(self._fast_keys.values()):
            fk.channel.flush()

    def _resolve_args_sync(self, spec: TaskSpec) -> Tuple[tuple, dict]:
        values = [ser.loads(payload) for _k, payload, _o in spec.args]
        nkw = len(spec.kwarg_keys)
        if nkw:
            return (tuple(values[:-nkw]),
                    dict(zip(spec.kwarg_keys, values[-nkw:])))
        return tuple(values), {}

    def _store_returns_sync(self, spec: TaskSpec, result: Any) -> dict:
        if spec.num_returns == 0:
            values: List[Any] = []
        elif spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"task {spec.name} declared num_returns="
                    f"{spec.num_returns} but returned {len(values)} values")
        returns = []
        for i, value in enumerate(values):
            oid = ObjectID.for_task_return(spec.task_id, i)
            returns.append([oid.binary(),
                            self._store_one_return_sync(oid, value)])
        return {"status": "ok", "returns": returns}

    def _store_one_return_sync(self, oid: ObjectID,
                               value: Any) -> Optional[bytes]:
        sobj = ser.serialize(value)
        if sobj.total_size <= self.config.max_direct_call_object_size or \
                self.plasma is None:
            return sobj.to_bytes()
        try:
            self.plasma.put_serialized(oid, sobj)
        except StoreFullError:
            return sobj.to_bytes()
        asyncio.run_coroutine_threadsafe(
            self.gcs.call("add_object_location", {
                "object_id": oid.binary(),
                "node_id": self.node_id.binary() if self.node_id else b""}),
            self.loop).result(timeout=30.0)
        return None

    def _store_exception_sync(self, spec: TaskSpec, e: Exception) -> dict:
        tb = traceback.format_exc()
        err = ser.RayTaskError(spec.function.display() if
                               spec.task_type != ACTOR_TASK else
                               spec.actor_method, tb, repr(e), cause=e
                               if _is_picklable(e) else None)
        blob = ser.dumps(err)
        return {"status": "error",
                "returns": [[oid.binary(), blob]
                            for oid in spec.return_ids()]}

    async def _resolve_args(self, spec: TaskSpec) -> Tuple[tuple, dict]:
        values = []
        for kind, payload, owner in spec.args:
            if kind == ARG_VALUE:
                values.append(ser.loads(payload))
            else:
                ref = ObjectRef(ObjectID(payload), owner_address=owner)
                values.append((await self.get_objects([ref]))[0])
        nkw = len(spec.kwarg_keys)
        if nkw:
            args = tuple(values[:-nkw])
            kwargs = dict(zip(spec.kwarg_keys, values[-nkw:]))
        else:
            args, kwargs = tuple(values), {}
        return args, kwargs

    def _execute_user_code(self, fn: Callable, args: tuple, kwargs: dict,
                           spec: Optional[TaskSpec] = None):
        """Runs on the executor thread. _current_task is set HERE (not on
        the loop around awaits) so pipelined task coroutines can't stomp
        each other's context — execution itself is serialized by the
        single-thread executor."""
        if spec is None:
            return fn(*args, **kwargs)
        prev = self._current_task
        self._current_task = spec
        try:
            return fn(*args, **kwargs)
        finally:
            self._current_task = prev

    # --- runtime-env isolation gate -------------------------------------
    # With pipelined task execution (max_tasks_in_flight_per_worker > 1),
    # a task that applies a runtime_env mutates process-global state
    # (os.environ, cwd, sys.path) across awaits. Such tasks take this gate
    # exclusively; plain tasks take it shared. Waiting env tasks block new
    # plain admissions so they can't be starved.
    def _env_gate_init(self) -> None:
        self._gate_cond = asyncio.Condition()
        self._gate_running = 0
        self._gate_env_active = False
        self._gate_env_waiting = 0

    async def _begin_task(self, exclusive: bool) -> None:
        if not hasattr(self, "_gate_cond"):
            self._env_gate_init()
        async with self._gate_cond:
            if exclusive:
                self._gate_env_waiting += 1
                try:
                    # Also wait out fastlane direct executions: they
                    # checked _env_seen before starting (Dekker pairing
                    # in _try_execute_direct), so once this predicate
                    # holds no user code can observe the env mid-apply.
                    await self._gate_cond.wait_for(
                        lambda: self._gate_running == 0 and
                        not self._gate_env_active and
                        self._direct_inflight == 0)
                finally:
                    self._gate_env_waiting -= 1
                self._gate_env_active = True
            else:
                await self._gate_cond.wait_for(
                    lambda: not self._gate_env_active and
                    self._gate_env_waiting == 0)
            self._gate_running += 1

    async def _end_task(self, exclusive: bool) -> None:
        async with self._gate_cond:
            self._gate_running -= 1
            if exclusive:
                self._gate_env_active = False
            self._gate_cond.notify_all()

    def _sync_gcs_call(self, method: str, data=None):
        """GCS call usable from executor threads (runtime_env fetch).
        MUST NOT be called on the event-loop thread (would deadlock) —
        _prefetch_runtime_env materializes packages off-loop first."""
        fut = asyncio.run_coroutine_threadsafe(
            self.gcs.call(method, data), self.loop)
        return fut.result(timeout=60.0)

    async def _prefetch_runtime_env(self, runtime_env) -> None:
        """Materialize env packages in an executor thread so the (sync)
        apply step on the loop thread only hits warm caches."""
        if not runtime_env:
            return
        from ray_tpu._private.runtime_env import _check_pip, _materialize

        loop = asyncio.get_running_loop()
        if runtime_env.get("pip"):
            # pip install can take minutes — never on the loop thread.
            await loop.run_in_executor(None, _check_pip, runtime_env)
        uris = []
        if runtime_env.get("working_dir"):
            uris.append(runtime_env["working_dir"])
        uris.extend(runtime_env.get("py_modules") or [])
        for uri in uris:
            await loop.run_in_executor(
                None, _materialize, uri, self._sync_gcs_call)

    _INLINE_MIN_OBSERVATIONS = 3

    async def _run_timed_sync(self, key, fn, *args):
        """Run sync user code, inline on the loop when its observed
        duration (EMA) is under the inline threshold — saving the
        executor-thread round trip (2 GIL handoffs) that dominates
        sub-millisecond task latency. Slow or unknown tasks keep the
        executor path (the loop must not stall on them), as do tasks
        ever observed calling the sync blocking API (get/put/wait can't
        run on the loop). A task that STARTS using the sync API after
        qualifying raises InlineUnsafeError before blocking; it is
        retried on the executor and its key barred from inlining."""
        threshold = self.config.inline_task_threshold_s
        state = self._exec_ema.get(key)
        inline = (threshold > 0 and not self._exec_direct and
                  state is not None and
                  state[1] >= self._INLINE_MIN_OBSERVATIONS and
                  state[0] < threshold and
                  key not in self._exec_sync_api_keys)
        t0 = time.monotonic()
        if inline and not self._exec_mutex.acquire(blocking=False):
            # A fastlane dispatcher (or the pump) is mid-execution: the
            # loop must never block on the mutex, so take the executor
            # path, which serializes behind it.
            inline = False
        if inline:
            self._inline_active = True
            retry_on_executor = False
            try:
                result = fn(*args)
            except InlineUnsafeError:
                self._exec_sync_api_keys.add(key)
                retry_on_executor = True
            finally:
                self._inline_active = False
                # Release BEFORE any await: the executor pump needs this
                # mutex, and it runs on another thread.
                self._exec_mutex.release()
            if retry_on_executor:
                result = await self._run_sync(fn, *args)
        else:
            def observed():
                _EXEC_TL.key = key
                try:
                    return fn(*args)
                finally:
                    _EXEC_TL.key = None

            result = await self._run_sync(observed)
        dt = time.monotonic() - t0
        if state is None:
            self._exec_ema[key] = [dt, 1]
        else:
            state[0] = 0.7 * state[0] + 0.3 * dt
            state[1] += 1
        return result

    async def _run_sync(self, fn, *args):
        if self._exec_direct:
            # Multi-threaded actor pool: parallel dispatch.
            return await asyncio.get_running_loop().run_in_executor(
                self._executor, fn, *args)
        fut = self.loop.create_future()
        with self._exec_lock:
            self._exec_queue.append((fn, args, fut))
            start = not self._exec_pump_running
            if start:
                self._exec_pump_running = True
        if start:
            self._executor.submit(self._exec_pump)
        return await fut

    def _exec_pump(self) -> None:
        """Runs in the executor thread: drains queued user-code calls.
        Amortizes the executor-thread WAKE over bursts (one submit per
        drain, not per task — ~50-80us of context switch + GIL handoff
        each on single-core hosts). Results post back immediately after
        each item: the next queued fn may be arbitrarily slow and must
        not delay replies for already-finished tasks."""
        while True:
            with self._exec_lock:
                item = (self._exec_queue.popleft()
                        if self._exec_queue else None)
                if item is None:
                    self._exec_pump_running = False
                    return
            fn, args, fut = item
            try:
                with self._exec_mutex:
                    result, err = fn(*args), None
            except BaseException as e:  # surfaced via the task's future
                result, err = None, e
            self.loop.call_soon_threadsafe(self._exec_resolve_one, fut,
                                           result, err)

    def _exec_resolve_one(self, fut, result, err) -> None:
        if fut.cancelled():
            return
        if err is None:
            fut.set_result(result)
        else:
            fut.set_exception(err)

    async def _fetch_function(self, descriptor: FunctionDescriptor):
        fn = self.function_manager.get_cached(descriptor)
        if fn is None:
            blob = await self.gcs.call("kv_get", {
                "ns": b"fn", "key": descriptor.function_key})
            fn = self.function_manager.load(descriptor, blob)
        return fn

    async def _execute_normal_task(self, spec: TaskSpec) -> dict:
        # The env must be live BEFORE function unpickle and argument
        # deserialization: shipped py_modules/working_dir code may be
        # referenced by the pickled payloads themselves. The env mutates
        # process-global state across awaits, so env-bearing tasks hold
        # the gate exclusively while pipelined plain tasks share it.
        exclusive = bool(spec.runtime_env)
        if exclusive:
            self._env_seen = True  # published before the gate wait
        await self._begin_task(exclusive)
        try:
            from ray_tpu._private.runtime_env import applied_runtime_env

            await self._prefetch_runtime_env(spec.runtime_env)
            with applied_runtime_env(spec.runtime_env,
                                     self._sync_gcs_call):
                fn = await self._fetch_function(spec.function)
                args, kwargs = await self._resolve_args(spec)
                exec_box: List[float] = []

                def _run_timed():
                    t0 = time.monotonic()
                    try:
                        return self._execute_user_code(fn, args, kwargs,
                                                       spec)
                    finally:
                        exec_box.append(time.monotonic() - t0)

                result = await self._run_timed_sync(
                    ("f", spec.function.function_key), _run_timed)
                exec_s = exec_box[0]
                if spec.is_streaming:
                    # The generator BODY runs during iteration, so it must
                    # stay inside the applied env, and the produce time —
                    # not the ~0s generator construction — is what feeds
                    # the pipelining gate.
                    t0 = time.monotonic()
                    reply = await self._store_streamed_returns(spec, result)
                    reply["exec_s"] = time.monotonic() - t0
                    return reply
            reply = await self._store_returns(spec, result)
            # Execution time feeds the submitter's pipelining gate
            # (_pump_scheduling_key): only observed-tiny tasks pipeline.
            reply["exec_s"] = exec_s
            return reply
        except Exception as e:
            return await self._store_exception(spec, e)
        finally:
            await self._end_task(exclusive)

    async def _execute_actor_creation(self, spec: TaskSpec) -> dict:
        _trace = os.environ.get("RAY_TPU_TRACE_STARTUP")
        _t0 = time.monotonic()

        def _tr(msg):
            if _trace:
                print(f"CRTRACE {os.getpid()} +{time.monotonic()-_t0:.3f}"
                      f" {msg}", flush=True)

        try:
            # Actor workers are dedicated to their actor: apply the env
            # permanently (visible to sync AND async methods, no
            # save/restore races under max_concurrency>1) — and BEFORE
            # unpickling, whose payloads may reference shipped modules.
            # Module-level import would also work, but the fork template
            # pre-imports runtime_env (forkserver.py) so this lazy form
            # stays free while keeping driver-side import light.
            from ray_tpu._private.runtime_env import \
                apply_runtime_env_permanent

            await self._prefetch_runtime_env(spec.runtime_env)
            apply_runtime_env_permanent(spec.runtime_env,
                                        self._sync_gcs_call)
            _tr("env applied")
            cls = await self._fetch_function(spec.function)
            _tr("function fetched")
            args, kwargs = await self._resolve_args(spec)
            _tr("args resolved")
            creation = spec.actor_creation_spec or {}
            max_concurrency = creation.get("max_concurrency", 1)
            instance = await self._run_sync(
                lambda: self._execute_user_code(cls, args, kwargs))
            _tr("user init done")
            self._local_actor = _LocalActor(instance, max_concurrency)
            self._local_actor_id = spec.actor_id
            if max_concurrency > 1:
                self._executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=max_concurrency,
                    thread_name_prefix="actor_exec")
                self._exec_direct = True  # parallel dispatch, no pump
            accepted = await self.gcs.call("actor_ready", {
                "actor_id": spec.actor_id.binary(),
                "address": self.address,
                "fast_address": self.fast_address,
                "node_id": self.node_id.binary() if self.node_id else b"",
            })
            _tr("actor_ready acked")
            if not accepted:
                # The actor was killed while its creation was in flight:
                # this dedicated worker must not linger holding the
                # lease — exit; the raylet reclaims on conn close.
                logger.info("actor %s was killed before ready; exiting",
                            spec.actor_id.hex()[:8])
                self._should_exit.set()
            return {"status": "ok", "returns": []}
        except Exception as e:
            tb = traceback.format_exc()
            logger.error("actor creation failed: %s", tb)
            try:
                await self.gcs.call("actor_creation_failed", {
                    "actor_id": spec.actor_id.binary(),
                    "error": f"{type(e).__name__}: {e}\n{tb}"})
            except Exception:
                pass
            return {"status": "error", "error": str(e), "returns": []}

    async def _execute_actor_task(self, spec: TaskSpec) -> dict:
        actor = self._local_actor
        if actor is None:
            return {"status": "error", "error": "no actor instance here",
                    "returns": []}
        async with actor.semaphore:
            try:
                if spec.actor_method == "__dag_loop__":
                    # Compiled-DAG loop install (ray_tpu/dag/compiled_dag.py):
                    # runs on the executor thread until channel teardown.
                    from ray_tpu.experimental.channel.exec_loop import \
                        run_dag_loop

                    (plan,), _ = await self._resolve_args(spec)
                    self._current_task = spec
                    result = await self._run_sync(
                        run_dag_loop, actor.instance, plan)
                    return await self._store_returns(spec, result)
                method = getattr(actor.instance, spec.actor_method)
                args, kwargs = await self._resolve_args(spec)
                self._current_task = spec
                if asyncio.iscoroutinefunction(method):
                    result = await method(*args, **kwargs)
                else:
                    # Actor env was applied permanently at creation.
                    result = await self._run_timed_sync(
                        ("m", spec.actor_method),
                        lambda: self._execute_user_code(method, args,
                                                        kwargs, spec))
                if spec.is_streaming:
                    return await self._store_streamed_returns(spec, result)
                return await self._store_returns(spec, result)
            except Exception as e:
                return await self._store_exception(spec, e)
            finally:
                self._current_task = None

    async def _store_returns(self, spec: TaskSpec, result: Any) -> dict:
        if spec.num_returns == 0:
            values: List[Any] = []
        elif spec.num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != spec.num_returns:
                raise ValueError(
                    f"task {spec.name} declared num_returns="
                    f"{spec.num_returns} but returned {len(values)} values")
        returns = []
        for i, value in enumerate(values):
            oid = ObjectID.for_task_return(spec.task_id, i)
            returns.append([oid.binary(),
                            await self._store_one_return(oid, value)])
        return {"status": "ok", "returns": returns}

    async def _store_one_return(self, oid: ObjectID,
                                value: Any) -> Optional[bytes]:
        """Store one return value: small → inline bytes (returned); large →
        local plasma + location registration (returns None)."""
        sobj = ser.serialize(value)
        if sobj.total_size <= self.config.max_direct_call_object_size or \
                self.plasma is None:
            return sobj.to_bytes()
        try:
            self.plasma.put_serialized(oid, sobj)
        except StoreFullError:
            return sobj.to_bytes()
        await self.gcs.call("add_object_location", {
            "object_id": oid.binary(),
            "node_id": self.node_id.binary() if self.node_id else b""})
        return None

    async def _store_exception(self, spec: TaskSpec, e: Exception) -> dict:
        tb = traceback.format_exc()
        err = ser.RayTaskError(spec.function.display() if
                               spec.task_type != ACTOR_TASK else
                               spec.actor_method, tb, repr(e), cause=e
                               if _is_picklable(e) else None)
        blob = ser.dumps(err)
        if spec.is_streaming:
            return {"status": "error", "returns": [],
                    "stream_total": 0, "stream_error": blob}
        return {"status": "error",
                "returns": [[oid.binary(), blob]
                            for oid in spec.return_ids()]}

    async def _store_streamed_returns(self, spec: TaskSpec,
                                      result: Any) -> dict:
        """Iterate the task's generator, reporting each yielded value to
        the caller while the task is still running (stream_item notifies),
        then return the completion reply carrying the produced count."""
        caller = spec.caller_address
        conn = None
        if caller and caller != self.address:
            conn = await self._peer(caller)

        if hasattr(result, "__anext__"):
            async def get_next():
                try:
                    return True, await result.__anext__()
                except StopAsyncIteration:
                    return False, None
        elif result is None or not hasattr(result, "__next__"):
            async def get_next():
                raise TypeError(
                    f"task {spec.name} declared num_returns='streaming' "
                    f"but returned {type(result).__name__}, not a "
                    f"generator/iterator")
        else:
            def _step():
                try:
                    return True, next(result)
                except StopIteration:
                    return False, None

            async def get_next():
                return await self._run_sync(_step)

        task_id = spec.task_id
        bp_limit = self.config.streaming_backpressure_num_items
        self._stream_producing.add(task_id)
        index = 0
        try:
            while True:
                # Producer-side backpressure: pause once bp_limit items
                # are yielded-but-unconsumed (reference:
                # _generator_backpressure_num_objects). Consumer acks
                # (stream_ack) advance the window; a re-check timeout
                # guards against lost acks and observes cancellation.
                while bp_limit > 0 and \
                        index - self._stream_acked.get(task_id, 0) >= \
                        bp_limit and task_id not in self._stream_cancels:
                    ev = self._stream_ack_events.setdefault(
                        task_id, asyncio.Event())
                    ev.clear()
                    try:
                        await asyncio.wait_for(ev.wait(), timeout=1.0)
                    except asyncio.TimeoutError:
                        pass
                if task_id in self._stream_cancels:
                    close = getattr(result, "aclose", None) or \
                        getattr(result, "close", None)
                    if close is not None:
                        r = close()
                        if asyncio.iscoroutine(r):
                            await r
                    break
                ok, value = await get_next()
                if not ok:
                    break
                oid = ObjectID.for_task_return(task_id, index)
                item = {"task_id": task_id.binary(), "index": index,
                        "data": await self._store_one_return(oid, value)}
                if conn is None:
                    self._accept_stream_item(item)
                else:
                    await conn.notify("stream_item", item)
                index += 1
        except Exception as e:
            tb = traceback.format_exc()
            err = ser.RayTaskError(
                spec.function.display() if spec.task_type != ACTOR_TASK
                else spec.actor_method, tb, repr(e),
                cause=e if _is_picklable(e) else None)
            return {"status": "error", "returns": [],
                    "stream_total": index, "stream_error": ser.dumps(err)}
        finally:
            self._stream_producing.discard(task_id)
            self._stream_cancels.discard(task_id)
            self._stream_acked.pop(task_id, None)
            self._stream_ack_events.pop(task_id, None)
        return {"status": "ok", "returns": [], "stream_total": index}

    async def handle_exit_worker(self, data, conn) -> None:
        logger.info("exit requested (force=%s)", data.get("force"))
        self._should_exit.set()
        if data.get("force"):
            os._exit(0)

    async def handle_ping(self, data, conn) -> str:
        return "pong"

    # ------------------------------------------------------------- task events
    def _record_task_event(self, spec: TaskSpec, state: str) -> None:
        if not self.config.task_events_enabled:
            return
        tc = spec.trace_ctx or {}
        with self._task_events_lock:
            self._task_events.append({
                "task_id": spec.task_id.binary(),
                "job_id": spec.job_id.binary(),
                "name": spec.name,
                "state": state,
                "time": time.time(),
                "worker_id": self.worker_id.binary(),
                "actor_id": spec.actor_id.binary() if spec.actor_id
                else None,
                "trace_id": tc.get("trace_id", ""),
                "parent_span_id": tc.get("parent_span_id", ""),
            })
        # Flush on batch size or a 1s cadence (reference: TaskEventBuffer
        # periodic flush, task_event_buffer.h:206).
        if len(self._task_events) >= self.config.task_events_batch_size or \
                time.time() - self._task_events_last_flush > 1.0:
            self._flush_task_events()

    def record_profile_event(self, name: str, start: float, end: float,
                             extra: Optional[dict] = None) -> None:
        """User span (reference: ProfileEvent, profile_event.h) — rides
        the task-event pipeline, shows up in `ray timeline`."""
        if not self.config.task_events_enabled:
            return
        with self._task_events_lock:
            self._task_events.append({
                "task_id": os.urandom(8),
                "job_id": self.job_id.binary() if self.job_id else b"",
                "name": name,
                "state": "PROFILE",
                "time": start,
                "end_time": end,
                "worker_id": self.worker_id.binary(),
                "actor_id": None,
                "extra": extra or {},
            })
        if len(self._task_events) >= self.config.task_events_batch_size or \
                time.time() - self._task_events_last_flush > 1.0:
            self._flush_task_events()

    def _flush_task_events(self) -> None:
        self._task_events_last_flush = time.time()
        with self._task_events_lock:
            events, self._task_events = self._task_events, []
        if events and self.gcs and not self.gcs.closed:
            asyncio.run_coroutine_threadsafe(
                self._send_events(events), self.loop)

    async def _send_events(self, events: List[dict]) -> None:
        try:
            await self.gcs.call("report_task_events", {"events": events})
        except Exception:
            pass


class _ObjectLost:
    def __init__(self, msg: str):
        self.msg = msg


_RETRY = object()


def _remaining(deadline: Optional[float]) -> Optional[float]:
    if deadline is None:
        return None
    return deadline - time.monotonic()


def _normalize_resources(opts: dict, task_type: int) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    default_cpu = 1.0 if task_type == NORMAL_TASK else 0.0
    num_cpus = opts.get("num_cpus")
    res["CPU"] = float(default_cpu if num_cpus is None else num_cpus)
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus"):
        res["GPU"] = float(opts["num_gpus"])
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    return {k: v for k, v in res.items() if v}


def _actor_method_descriptor(method: str) -> FunctionDescriptor:
    return FunctionDescriptor(module="", qualname=method, function_key=b"")


def _all_methods_plain(instance) -> bool:
    """True when every public method is a plain sync function (no
    coroutine/generator methods): the precondition for fastlane direct
    execution of a max_concurrency=1 actor — the loop-side semaphore is
    the concurrency authority for anything fancier."""
    import inspect

    cls = type(instance)
    for name in dir(cls):
        if name.startswith("__"):
            continue
        fn = getattr(cls, name, None)
        if fn is None or not callable(fn):
            continue
        if asyncio.iscoroutinefunction(fn) or \
                inspect.isgeneratorfunction(fn) or \
                inspect.isasyncgenfunction(fn):
            return False
    return True


def _is_picklable(e: Exception) -> bool:
    import pickle

    try:
        pickle.dumps(e)
        return True
    except Exception:
        return False
