"""Worker process entrypoint.

Equivalent of the reference's default_worker.py (python/ray/_private/
workers/default_worker.py): spawned by the raylet, connects back, serves
push_task RPCs until told to exit. TPU visibility env vars
(TPU_VISIBLE_CHIPS etc.) are set by the raylet before spawn when the lease
carries TPU resources.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os


def main() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s worker %(levelname)s %(message)s")
    # Driver sys.path (shipped via the raylet) so functions pickled by
    # reference from driver-side modules (e.g. test files) import here.
    import sys

    for p in reversed(
            os.environ.get("RAY_TPU_DRIVER_SYS_PATH", "").split(":")):
        if p and p not in sys.path:
            sys.path.insert(0, p)
    from ray_tpu.core.config import Config
    from ray_tpu.core.ids import NodeID, WorkerID
    from ray_tpu._private.core_worker import WORKER, CoreWorker

    async def amain():
        import time as _time

        trace = os.environ.get("RAY_TPU_TRACE_STARTUP")
        t_start = _time.time()

        def tr(msg):
            if trace:
                print(f"TRACE {os.getpid()} +{_time.time() - t_start:.3f} "
                      f"{msg}", flush=True)

        tr("amain begin")
        cfg_json = os.environ.get("RAY_TPU_CONFIG_JSON")
        config = Config.from_dict(json.loads(cfg_json)) if cfg_json \
            else Config.from_env()
        cw = CoreWorker(
            mode=WORKER,
            gcs_address=os.environ["RAY_TPU_GCS_ADDRESS"],
            config=config,
            loop=asyncio.get_running_loop(),
            raylet_address=os.environ["RAY_TPU_RAYLET_ADDRESS"],
            store_path=os.environ.get("RAY_TPU_STORE_PATH"),
            node_id=NodeID.from_hex(os.environ["RAY_TPU_NODE_ID"]),
            session_dir=os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu"),
            worker_id=WorkerID.from_hex(os.environ["RAY_TPU_WORKER_ID"]),
        )
        # Make this worker the process-global worker so user code running in
        # tasks can call ray_tpu.get/put/remote recursively.
        from ray_tpu._private import worker as worker_mod

        worker_mod._attach_executor_worker(cw)
        tr("connecting")
        await cw.connect()
        tr("connected (registered with raylet)")
        await cw._should_exit.wait()
        await cw.disconnect()

    profile_dir = os.environ.get("RAY_TPU_WORKER_PROFILE")
    if profile_dir:
        import signal
        import sys as _sys

        signal.signal(signal.SIGTERM, lambda *_: _sys.exit(0))
        # Debug aid: cProfile the whole worker (loop thread) and dump
        # stats at exit — the only way to see inside spawned workers in
        # environments without py-spy/perf.
        import cProfile

        prof = cProfile.Profile()
        try:
            prof.runcall(asyncio.run, amain())
        finally:
            os.makedirs(profile_dir, exist_ok=True)
            prof.dump_stats(os.path.join(
                profile_dir, f"worker_{os.getpid()}.prof"))
    else:
        asyncio.run(amain())


if __name__ == "__main__":
    main()
