"""Usage stats — opt-out telemetry recording (reference:
python/ray/_private/usage/usage_lib.py).

This deployment is hermetic (zero egress), so nothing is ever
transmitted; the record is written next to the session logs for
operators who want it, and RAY_TPU_USAGE_STATS_ENABLED=0 disables even
that. API parity: usage_stats_enabled(), record_extra_usage_tag().
"""

from __future__ import annotations

import json
import os
import platform
import sys
import time
from typing import Dict

_TAGS: Dict[str, str] = {}


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1") == "1"


def record_extra_usage_tag(key: str, value: str) -> None:
    _TAGS[str(key)] = str(value)


def write_usage_record(session_dir: str) -> None:
    """Local-only usage snapshot (never leaves the machine)."""
    if not usage_stats_enabled():
        return
    try:
        import ray_tpu

        record = {
            "schema_version": 1,
            "timestamp": time.time(),
            "ray_tpu_version": ray_tpu.__version__,
            "python_version": sys.version.split()[0],
            "platform": platform.platform(),
            "extra_tags": dict(_TAGS),
        }
        os.makedirs(session_dir, exist_ok=True)
        with open(os.path.join(session_dir, "usage_stats.json"),
                  "w") as f:
            json.dump(record, f)
    except Exception:
        pass
