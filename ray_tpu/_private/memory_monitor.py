"""Node memory monitor + OOM worker-killing policy.

Reference: src/ray/common/memory_monitor.h + raylet worker-killing
policies (worker_killing_policy.h:34 — retriable-FIFO: kill the most
recently started retriable work first, so long-running work survives).
The raylet polls usage; past the threshold it kills the newest leased
worker (its task retries per max_retries) before the kernel OOM killer
takes down the raylet itself.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple


def get_system_memory_bytes() -> Tuple[int, int]:
    """(used, total) honoring cgroup v2 limits when present (containers)."""
    total = used = 0
    try:
        with open("/proc/meminfo") as f:
            info = {}
            for line in f:
                parts = line.split()
                info[parts[0].rstrip(":")] = int(parts[1]) * 1024
        total = info.get("MemTotal", 0)
        available = info.get("MemAvailable", 0)
        used = total - available
    except OSError:
        return 0, 0
    # cgroup v2: a tighter container limit wins.
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw != "max":
            cg_total = int(raw)
            if 0 < cg_total < total:
                with open("/sys/fs/cgroup/memory.current") as f:
                    cg_used = int(f.read().strip())
                return cg_used, cg_total
    except (OSError, ValueError):
        pass
    return used, total


def memory_usage_fraction() -> float:
    used, total = get_system_memory_bytes()
    if total <= 0:
        return 0.0
    return used / total


def pick_worker_to_kill(workers) -> Optional[object]:
    """Retriable-FIFO analog: newest leased worker first (its lease began
    last, so the least progress is lost and its task retries); never the
    raylet's idle pool, never actors (actor restart is heavier — the
    reference's group-by-owner policy also deprioritizes them)."""
    leased = [w for w in workers if w.state == "leased"]
    if leased:
        return max(leased, key=lambda w: getattr(w, "lease_started", 0.0))
    return None
