"""Ownership-based distributed reference counting.

Equivalent of the reference's ReferenceCounter
(src/ray/core_worker/reference_count.cc): every object has exactly one owner
(the worker that created it — by `put` or by submitting the producing task).
The owner tracks:
  - local references (ObjectRef instances alive in the owner process),
  - submitted-task references (the object is an argument of an in-flight task),
  - borrower processes (processes that deserialized a ref to this object).
When all counts reach zero the object is out of scope: it is deleted from
the memory store and the shm store, and borrower notifications stop.

Borrowers track local refs per borrowed object and notify the owner when
their count drops to zero (ref_removed RPC to the owner address).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set

from ray_tpu.core.ids import ObjectID


class _Ref:
    __slots__ = ("local", "submitted", "borrowers", "owned", "owner_address",
                 "lineage_task", "pinned")

    def __init__(self):
        self.local = 0
        self.submitted = 0
        self.borrowers: Set[str] = set()
        self.owned = False
        self.owner_address: Optional[str] = None
        self.lineage_task = None  # TaskSpec that can reproduce the object
        self.pinned = False

    def out_of_scope(self) -> bool:
        return self.local <= 0 and self.submitted <= 0 and not self.borrowers \
            and not self.pinned


class ReferenceCounter:
    def __init__(self, on_object_out_of_scope: Optional[Callable] = None,
                 notify_owner_ref_removed: Optional[Callable] = None):
        self._refs: Dict[ObjectID, _Ref] = {}
        self._lock = threading.RLock()
        # owner-side: delete the object everywhere
        self._on_out_of_scope = on_object_out_of_scope
        # borrower-side: tell the owner we dropped our refs
        self._notify_owner = notify_owner_ref_removed

    def _get(self, object_id: ObjectID) -> _Ref:
        ref = self._refs.get(object_id)
        if ref is None:
            ref = self._refs[object_id] = _Ref()
        return ref

    # --- owner registration ---
    def add_owned_object(self, object_id: ObjectID,
                         lineage_task=None) -> None:
        with self._lock:
            ref = self._get(object_id)
            ref.owned = True
            ref.lineage_task = lineage_task

    def add_borrowed_object(self, object_id: ObjectID,
                            owner_address: str) -> None:
        with self._lock:
            ref = self._get(object_id)
            if not ref.owned:
                ref.owner_address = owner_address

    def is_owned(self, object_id: ObjectID) -> bool:
        with self._lock:
            ref = self._refs.get(object_id)
            return bool(ref and ref.owned)

    def owner_address(self, object_id: ObjectID) -> Optional[str]:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.owner_address if ref else None

    def get_lineage(self, object_id: ObjectID):
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.lineage_task if ref else None

    def pin(self, object_id: ObjectID, pinned: bool = True) -> None:
        with self._lock:
            self._get(object_id).pinned = pinned

    # --- local refs (ObjectRef lifecycle) ---
    def add_local_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._get(object_id).local += 1

    def remove_local_ref(self, object_id: ObjectID) -> None:
        self._decrement(object_id, "local")

    # --- submitted-task refs ---
    def add_submitted_task_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._get(object_id).submitted += 1

    def remove_submitted_task_ref(self, object_id: ObjectID) -> None:
        self._decrement(object_id, "submitted")

    # --- borrowers (owner side) ---
    def add_borrower(self, object_id: ObjectID, borrower_address: str) -> None:
        with self._lock:
            self._get(object_id).borrowers.add(borrower_address)

    def remove_borrower(self, object_id: ObjectID,
                        borrower_address: str) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if not ref:
                return
            ref.borrowers.discard(borrower_address)
            self._maybe_out_of_scope(object_id, ref)

    def _decrement(self, object_id: ObjectID, field: str) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            setattr(ref, field, getattr(ref, field) - 1)
            self._maybe_out_of_scope(object_id, ref)

    def _maybe_out_of_scope(self, object_id: ObjectID, ref: _Ref) -> None:
        if not ref.out_of_scope():
            return
        self._refs.pop(object_id, None)
        if ref.owned:
            if self._on_out_of_scope:
                self._on_out_of_scope(object_id)
        elif ref.owner_address and self._notify_owner:
            self._notify_owner(object_id, ref.owner_address)

    def num_refs(self) -> int:
        with self._lock:
            return len(self._refs)

    def summary(self) -> dict:
        with self._lock:
            return {
                oid.hex(): {
                    "local": r.local, "submitted": r.submitted,
                    "borrowers": len(r.borrowers), "owned": r.owned,
                }
                for oid, r in self._refs.items()
            }
