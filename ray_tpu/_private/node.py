"""Node — process orchestration for cluster bring-up.

Equivalent of the reference's Node + services (python/ray/_private/node.py:37,
services.py:1439,1504): creates the session directory, sizes and creates the
shm object store, and spawns the GCS server (head only) and the raylet as
separate processes, reading their bound ports off stdout.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time
from typing import Dict, Optional

from ray_tpu.core.config import Config
from ray_tpu.core.ids import NodeID
from ray_tpu.core.shm_client import ShmClient


def default_resources() -> Dict[str, float]:
    from ray_tpu._private.accelerators import detect_tpu_chips

    res: Dict[str, float] = {"CPU": float(os.cpu_count() or 1)}
    chips = detect_tpu_chips()
    if chips:
        res["TPU"] = float(chips)
    return res


def auto_store_bytes(config: Config) -> int:
    if config.object_store_memory:
        return config.object_store_memory
    try:
        free = shutil.disk_usage("/dev/shm").free
    except OSError:
        free = 1 << 30
    return int(min(free * config.object_store_auto_fraction,
                   config.object_store_max_auto_bytes))


def _read_json_line(proc: subprocess.Popen, timeout: float,
                    what: str) -> dict:
    deadline = time.monotonic() + timeout
    line = ""
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited with code {proc.returncode} before "
                f"announcing its port")
        line = proc.stdout.readline().decode()
        if line.strip():
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue  # stray log line on stdout
    raise TimeoutError(f"{what} did not announce its port (last: {line!r})")


class ProcessHandle:
    def __init__(self, proc: subprocess.Popen, name: str):
        self.proc = proc
        self.name = name

    def alive(self) -> bool:
        return self.proc.poll() is None

    def terminate(self) -> None:
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                self.proc.kill()


class Node:
    """Starts (head) or joins a ray_tpu cluster on this machine."""

    def __init__(self, config: Config,
                 resources: Optional[Dict[str, float]] = None,
                 gcs_address: Optional[str] = None,
                 session_dir: Optional[str] = None,
                 labels: Optional[Dict[str, str]] = None,
                 slice_id: str = "",
                 node_name: str = "node"):
        self.config = config
        self.is_head = gcs_address is None
        self.gcs_address = gcs_address
        self.resources = resources or default_resources()
        self.labels = labels or {}
        self.slice_id = slice_id
        self.node_id = NodeID.from_random()
        self.processes: list[ProcessHandle] = []
        if session_dir is None:
            session_dir = os.path.join(
                self.config.temp_dir,
                f"session_{int(time.time() * 1000)}_{os.getpid()}")
        self.session_dir = session_dir
        os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
        self.store_path = f"/dev/shm/ray_tpu_{self.node_id.hex()[:12]}"
        self.raylet_address: Optional[str] = None

    def start(self) -> None:
        store_bytes = auto_store_bytes(self.config)
        ShmClient.create_store(self.store_path, store_bytes)
        if self.is_head:
            self._start_gcs()
        self._start_raylet()

    def _spawn(self, args: list, name: str) -> subprocess.Popen:
        log = open(os.path.join(self.session_dir, "logs", f"{name}.err"), "ab")
        import ray_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        env = {**os.environ, "RAY_TPU_CONFIG_JSON": self.config.to_json()}
        env["PYTHONPATH"] = pkg_root + (
            ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        # Ship the driver's sys.path so workers can unpickle functions
        # defined in driver-side modules (reference: JobConfig
        # py_driver_sys_path propagated to default_worker.py).
        env.setdefault("RAY_TPU_DRIVER_SYS_PATH",
                       ":".join(p for p in sys.path if p))
        # Control-plane processes never touch JAX; skip the TPU plugin
        # registration hook (sitecustomize) that would import jax (~2s).
        # The raylet restores it for worker processes on TPU nodes.
        pool_ips = env.pop("PALLAS_AXON_POOL_IPS", None)
        if pool_ips:
            env["RAY_TPU_AXON_POOL_IPS"] = pool_ips
        proc = subprocess.Popen(
            [sys.executable, "-u", "-m"] + args,
            stdout=subprocess.PIPE, stderr=log, start_new_session=True,
            env=env)
        log.close()
        self.processes.append(ProcessHandle(proc, name))
        return proc

    def _start_gcs(self, port: int = 0) -> None:
        persist = os.path.join(self.session_dir, "gcs_tables.sqlite")
        proc = self._spawn(["ray_tpu._private.gcs_server",
                            "--config", self.config.to_json(),
                            "--port", str(port),
                            "--persist-path", persist], "gcs")
        info = _read_json_line(proc, 30, "gcs_server")
        self.gcs_address = f"127.0.0.1:{info['port']}"
        self._gcs_proc = proc

    def restart_gcs(self) -> None:
        """Restart a dead GCS on the SAME port: state comes back from the
        write-through table storage, raylets and workers re-register over
        their reconnect paths (reference: GCS fault tolerance via Redis
        persistence + HandleNotifyGCSRestart)."""
        port = int(self.gcs_address.rsplit(":", 1)[1])
        self.processes = [p for p in self.processes
                          if p.proc is not getattr(self, "_gcs_proc", None)]
        self._start_gcs(port=port)

    def kill_gcs(self) -> None:
        """Kill the GCS process (fault-injection hook for tests)."""
        proc = getattr(self, "_gcs_proc", None)
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=5)

    def _start_raylet(self) -> None:
        proc = self._spawn([
            "ray_tpu._private.raylet",
            "--gcs-address", self.gcs_address,
            "--store-path", self.store_path,
            "--resources", json.dumps(self.resources),
            "--session-dir", self.session_dir,
            "--node-id", self.node_id.hex(),
            "--labels", json.dumps(self.labels),
            "--slice-id", self.slice_id,
            "--config", self.config.to_json(),
        ], f"raylet-{self.node_id.hex()[:8]}")
        info = _read_json_line(proc, 30, "raylet")
        self.raylet_address = f"127.0.0.1:{info['port']}"

    def kill_raylet(self) -> None:
        """Test/chaos hook: kill this node's raylet process."""
        for p in self.processes:
            if p.name.startswith("raylet"):
                p.terminate()

    def shutdown(self) -> None:
        for p in reversed(self.processes):
            p.terminate()
        self.processes.clear()
        try:
            os.unlink(self.store_path)
        except OSError:
            pass
