"""Process-local metric registry with GCS push.

Reference: src/ray/stats/ (OpenCensus registry in every process) +
python/ray/_private/metrics_agent.py (per-node agent re-exposing
Prometheus). Simplification, same shape: every process registers metrics
locally and pushes snapshots to the GCS on a short cadence; the dashboard
exposes the aggregate as Prometheus text.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

_lock = threading.Lock()
_registry: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], "_Metric"] = {}
_pusher: Optional[threading.Thread] = None
_push_stop = threading.Event()

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 50, 100, 500, 1000]


class _Metric:
    def __init__(self, name: str, kind: str, description: str,
                 tags: Dict[str, str],
                 boundaries: Optional[List[float]] = None):
        self.name = name
        self.kind = kind  # counter | gauge | histogram
        self.description = description
        self.tags = dict(tags)
        self.value = 0.0
        self.boundaries = boundaries or []
        self.bucket_counts = [0] * (len(self.boundaries) + 1)
        self.sum = 0.0
        self.count = 0

    def snapshot(self) -> Dict[str, Any]:
        out = {"name": self.name, "kind": self.kind,
               "description": self.description, "tags": self.tags,
               "value": self.value}
        if self.kind == "histogram":
            out.update({"boundaries": self.boundaries,
                        "bucket_counts": self.bucket_counts,
                        "sum": self.sum, "count": self.count})
        return out


def register(name: str, kind: str, description: str,
             tags: Dict[str, str],
             boundaries: Optional[List[float]] = None) -> _Metric:
    key = (name, tuple(sorted(tags.items())))
    with _lock:
        metric = _registry.get(key)
        if metric is None:
            metric = _registry[key] = _Metric(name, kind, description,
                                              tags, boundaries)
        return metric


def record(metric: _Metric, value: float, kind: str) -> None:
    with _lock:
        if kind == "counter":
            metric.value += value
        elif kind == "gauge":
            metric.value = value
        else:
            metric.sum += value
            metric.count += 1
            idx = 0
            while idx < len(metric.boundaries) and \
                    value > metric.boundaries[idx]:
                idx += 1
            metric.bucket_counts[idx] += 1


def snapshots() -> List[Dict[str, Any]]:
    with _lock:
        return [m.snapshot() for m in _registry.values()]


def reset_registry() -> None:
    """Drop every registered series (TEST ISOLATION, not production):
    the process-local registry is module state, so counters recorded by
    one test module would otherwise leak into the next module's
    snapshots()/prometheus_text() assertions. Metric objects held by
    callers (EngineMetrics instruments, fleet gauge caches) stay valid
    — register() lazily re-creates a series on the next record."""
    with _lock:
        _registry.clear()


# -- Prometheus text exposition ---------------------------------------------
#
# The ONE renderer for metric snapshots -> exposition format, shared by
# the dashboard head's /metrics route (GCS-aggregated rows) and
# util.metrics.prometheus_text() (this process's registry). Keeping it
# next to the registry means the snapshot dict shape and its renderer
# can never drift apart.

def escape_label(value: str) -> str:
    """Prometheus exposition-format label escaping (backslash, quote,
    newline) — unescaped user tag values would break the whole scrape."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text: str) -> str:
    """HELP-line escaping: backslash and newline only (the format
    leaves quotes alone there)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_text(rows: Optional[List[Dict[str, Any]]] = None,
                    prefix: str = "ray_tpu_") -> str:
    """Render metric snapshot rows (`snapshots()` by default) as
    Prometheus text exposition: one `# HELP` / `# TYPE` header per
    metric with every series of that metric grouped under it (the
    format REQUIRES samples of one metric to be contiguous), sorted
    label rendering, and cumulative histogram `_bucket{le=...}` lines
    ending in the implicit `+Inf` bucket plus `_sum` / `_count`.
    Metric names are mangled `<prefix> + name.replace('.', '_')` —
    `util.metrics` dots become Prometheus underscores."""
    if rows is None:
        rows = snapshots()
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for m in rows:
        name = prefix + m["name"].replace(".", "_")
        groups.setdefault(name, []).append(m)
    lines: List[str] = []
    for name, ms in groups.items():
        first = ms[0]
        if first.get("description"):
            lines.append(
                f"# HELP {name} {_escape_help(first['description'])}")
        kind = {"counter": "counter", "gauge": "gauge",
                "histogram": "histogram"}[first["kind"]]
        lines.append(f"# TYPE {name} {kind}")
        for m in ms:
            tag_str = ",".join(f'{k}="{escape_label(v)}"'
                               for k, v in sorted(m["tags"].items()))
            label = f"{{{tag_str}}}" if tag_str else ""
            if m["kind"] == "histogram":
                cumulative = 0
                bounds = m.get("boundaries", [])
                for i, c in enumerate(m.get("bucket_counts", [])):
                    cumulative += c
                    le = bounds[i] if i < len(bounds) else "+Inf"
                    extra = f'le="{le}"'
                    tags = (f"{{{tag_str},{extra}}}" if tag_str
                            else f"{{{extra}}}")
                    lines.append(f"{name}_bucket{tags} {cumulative}")
                lines.append(f"{name}_sum{label} {m.get('sum', 0)}")
                lines.append(f"{name}_count{label} {m.get('count', 0)}")
            else:
                lines.append(f"{name}{label} {m['value']}")
    return "\n".join(lines) + "\n"


def _push_loop(interval_s: float) -> None:
    from ray_tpu._private.worker import global_worker_or_none

    while not _push_stop.wait(interval_s):
        worker = global_worker_or_none()
        if worker is None:
            continue
        snaps = snapshots()
        if not snaps:
            continue
        try:
            worker.gcs_call("report_metrics", {
                "worker_id": worker.core.worker_id.binary(),
                "metrics": snaps})
        except Exception:
            pass


def ensure_pusher(interval_s: float = 2.0) -> None:
    global _pusher
    with _lock:
        if _pusher is None or not _pusher.is_alive():
            _push_stop.clear()
            _pusher = threading.Thread(
                target=_push_loop, args=(interval_s,), daemon=True,
                name="metrics-pusher")
            _pusher.start()


def flush_now() -> None:
    """Synchronous push (tests / shutdown)."""
    from ray_tpu._private.worker import global_worker_or_none

    worker = global_worker_or_none()
    if worker is None:
        return
    snaps = snapshots()
    if snaps:
        worker.gcs_call("report_metrics", {
            "worker_id": worker.core.worker_id.binary(),
            "metrics": snaps})
