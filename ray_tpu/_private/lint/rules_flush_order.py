"""flush-order: admission/slot-table mutation must be flush-dominated.

With ``pipeline_depth > 1`` the engine keeps un-drained dispatches in
``self._ring``; each in-flight step snapshotted the slot tables at dispatch
time.  Mutating admission state while the ring is non-empty (admitting into
a row a queued dispatch still writes, popping the scheduler, rebinding
prefill state) corrupts the snapshot the drain path will commit against —
the PR-5 ring invariant that ``step()`` enforces by hand with its
flush-before-admission call sites.

The rule encodes that discipline per class that defines
``_flush_pipeline``:

* **sensitive mutations** — subscript stores / ``del`` / ``.pop()`` /
  ``.clear()`` on the admission state attributes (``row_req``,
  ``row_len``, ``row_budget``, ``_tok_idx``, ``_row_prefill``) and
  ``self.scheduler.pop()``.  Block-table growth (``_row_blocks`` /
  ``_bt``) is deliberately NOT sensitive: ``_top_up_pipeline`` legally
  grows block chains mid-flight because the device snapshotted the block
  table at dispatch.
* **dominators** — an earlier ``self._flush_pipeline(...)`` call
  (including the conditional flush-already-done form), an
  ``assert not self._ring`` precondition, or ``self._ring.clear()``.
  Dominance is approximated by source order within the method body.
* **propagation** — a method is *needy* when a sensitive mutation (or a
  call to a needy method) precedes its first dominator; neediness flows
  up the class-local call graph to a fixpoint.  Findings are emitted only
  at the boundary where the obligation escapes static view: needy
  **public** methods (anyone may call them mid-flight) and needy private
  methods with **no class-local callers**.  Needy helpers reached only
  from dominated callers (``step()`` flushes, then admits) are the
  sanctioned shape and stay silent.
* the flush machinery itself (``_flush_pipeline``, ``_drain_one``,
  ``_emit_block``) and ``__init__`` are exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ray_tpu._private.lint.core import FileContext, Finding, Rule, register
from ray_tpu._private.lint.dataflow import call_tail

SENSITIVE_ATTRS = frozenset(
    {"row_req", "row_len", "row_budget", "_tok_idx", "_row_prefill"}
)
_MUTATING_METHODS = frozenset({"pop", "clear", "popitem"})
_EXEMPT = frozenset(
    {"_flush_pipeline", "_drain_one", "_emit_block", "__init__"}
)
_RING_ATTRS = frozenset({"_ring"})


def _self_attr(node: ast.AST) -> str:
    """`self.<attr>`/`self.<attr>[...]` -> attr name, else ""."""
    cur = node
    if isinstance(cur, ast.Subscript):
        cur = cur.value
    if isinstance(cur, ast.Attribute) and \
            isinstance(cur.value, ast.Name) and cur.value.id == "self":
        return cur.attr
    return ""


class _MethodFacts:
    __slots__ = ("name", "node", "first_dominator", "mutations", "calls")

    def __init__(self, name: str, node: ast.AST):
        self.name = name
        self.node = node
        self.first_dominator: Optional[int] = None
        # [(lineno, node, description)]
        self.mutations: List[tuple] = []
        # [(lineno, node, callee_name)]
        self.calls: List[tuple] = []


@register
class FlushOrderRule(Rule):
    name = "flush-order"
    description = (
        "admission-state/slot-table mutation in a pipelined engine must be "
        "dominated by _flush_pipeline (or a drained-ring guard)"
    )

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef) and any(
                isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef))
                and c.name == "_flush_pipeline"
                for c in node.body
            ):
                findings.extend(self._check_class(ctx, node))
        return findings

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> List[Finding]:
        facts: Dict[str, _MethodFacts] = {}
        for child in cls.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                facts[child.name] = self._scan_method(child)

        callers: Dict[str, Set[str]] = {name: set() for name in facts}
        for name, mf in facts.items():
            for _line, _node, callee in mf.calls:
                if callee in callers and callee != name:
                    callers[callee].add(name)

        # Fixpoint: needy = mutation or needy-callee call before the first
        # dominator (source order).
        needy: Dict[str, Optional[tuple]] = {}   # name -> offending site
        for name, mf in facts.items():
            if name in _EXEMPT:
                continue
            site = self._first_undominated(mf, set())
            if site is not None:
                needy[name] = site
        changed = True
        while changed:
            changed = False
            for name, mf in facts.items():
                if name in _EXEMPT or name in needy:
                    continue
                site = self._first_undominated(mf, set(needy))
                if site is not None:
                    needy[name] = site
                    changed = True

        findings: List[Finding] = []
        for name, site in sorted(needy.items()):
            public = not name.startswith("_")
            orphan = not callers.get(name)
            if not (public or orphan):
                continue   # private, only reachable via dominated callers
            line, node, what = site
            how = ("public entry point" if public
                   else "no class-local caller establishes the flush")
            findings.append(ctx.finding(
                self.name,
                node,
                f"{what} while the dispatch ring may be non-empty "
                f"({how}); call _flush_pipeline (or assert a drained ring) "
                "first",
            ))
        return findings

    # -- per-method scan -----------------------------------------------------

    def _scan_method(self, fn: ast.AST) -> _MethodFacts:
        mf = _MethodFacts(fn.name, fn)
        for node in self._own_nodes(fn):
            line = getattr(node, "lineno", 0)
            if self._is_dominator(node):
                if mf.first_dominator is None or line < mf.first_dominator:
                    mf.first_dominator = line
                continue
            mut = self._mutation_desc(node)
            if mut is not None:
                mf.mutations.append((line, node, mut))
            elif isinstance(node, ast.Call):
                attr = ""
                if isinstance(node.func, ast.Attribute) and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id == "self":
                    attr = node.func.attr
                if attr:
                    mf.calls.append((line, node, attr))
        mf.mutations.sort(key=lambda t: t[0])
        mf.calls.sort(key=lambda t: t[0])
        return mf

    def _first_undominated(self, mf: _MethodFacts,
                           needy: Set[str]) -> Optional[tuple]:
        dom = mf.first_dominator
        for line, node, what in mf.mutations:
            if dom is None or line < dom:
                return (line, node, what)
        for line, node, callee in mf.calls:
            if callee in needy and (dom is None or line < dom):
                return (line, node,
                        f"call to `{callee}()` which mutates admission "
                        "state")
        return None

    def _is_dominator(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            if call_tail(node) == "_flush_pipeline":
                return True
            # self._ring.clear(): the ring is empty afterwards
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "clear" and \
                    _self_attr(node.func.value) in _RING_ATTRS:
                return True
            return False
        if isinstance(node, ast.Assert):
            test = node.test
            if isinstance(test, ast.UnaryOp) and \
                    isinstance(test.op, ast.Not) and \
                    _self_attr(test.operand) in _RING_ATTRS:
                return True
        return False

    def _mutation_desc(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    attr = _self_attr(tgt)
                    if attr in SENSITIVE_ATTRS:
                        return f"write to `self.{attr}[...]`"
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Subscript):
                attr = _self_attr(node.target)
                if attr in SENSITIVE_ATTRS:
                    return f"in-place update of `self.{attr}[...]`"
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript):
                    attr = _self_attr(tgt)
                    if attr in SENSITIVE_ATTRS:
                        return f"`del self.{attr}[...]`"
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            method = node.func.attr
            recv = node.func.value
            if method in _MUTATING_METHODS:
                attr = _self_attr(recv)
                if attr in SENSITIVE_ATTRS:
                    return f"`self.{attr}.{method}()`"
            if method == "pop" and isinstance(recv, ast.Attribute) and \
                    recv.attr == "scheduler" and \
                    isinstance(recv.value, ast.Name) and \
                    recv.value.id == "self":
                return "`self.scheduler.pop()`"
        return None

    @staticmethod
    def _own_nodes(fn: ast.AST):
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))
