"""graftlint: repo-invariant static analysis + sanitizer glue for ray_tpu.

Public surface re-exported from :mod:`ray_tpu._private.lint.core`; the
analyzers self-register on import via :func:`default_rules`.  v2 adds the
interprocedural layer (:mod:`.dataflow`) and the kv-refcount / flush-order /
sharding-pin invariant analyzers.
"""

from ray_tpu._private.lint.core import (
    DEFAULT_BASELINE,
    Finding,
    LintConfig,
    LintReport,
    RULE_REGISTRY,
    Rule,
    baseline_entries,
    default_rules,
    diff_baseline,
    iter_python_files,
    lint_paths,
    lint_source,
    load_baseline,
    register,
    save_baseline,
)

__all__ = [
    "DEFAULT_BASELINE",
    "Finding",
    "LintConfig",
    "LintReport",
    "RULE_REGISTRY",
    "Rule",
    "baseline_entries",
    "default_rules",
    "diff_baseline",
    "iter_python_files",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "register",
    "save_baseline",
]
