"""graftlint: repo-invariant static analysis + sanitizer glue for ray_tpu.

Public surface re-exported from :mod:`ray_tpu._private.lint.core`; the four
analyzers self-register on import via :func:`default_rules`.
"""

from ray_tpu._private.lint.core import (
    DEFAULT_BASELINE,
    Finding,
    LintConfig,
    LintReport,
    RULE_REGISTRY,
    Rule,
    baseline_entries,
    default_rules,
    diff_baseline,
    lint_paths,
    lint_source,
    load_baseline,
    register,
    save_baseline,
)

__all__ = [
    "DEFAULT_BASELINE",
    "Finding",
    "LintConfig",
    "LintReport",
    "RULE_REGISTRY",
    "Rule",
    "baseline_entries",
    "default_rules",
    "diff_baseline",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "register",
    "save_baseline",
]
