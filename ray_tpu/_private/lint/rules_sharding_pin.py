"""sharding-pin: host-updated donated carries must be re-pinned.

The fused dispatch donates its carries (``cache``, ``pool_k/v``,
``last_logits``, draft-plane twins); inside jit every carry is re-pinned
with ``with_sharding_constraint`` so tensor-parallel layouts survive the
donation.  The hazard is the HOST side: when the engine rebuilds a carry
between dispatches (``jnp.zeros`` at init, ``.at[row].set(...)`` on swap-in,
an ``np``->``jnp`` round trip), the fresh array materialises with default
(replicated / single-device) placement — and the next dispatch silently
runs with a decayed layout, correct but devastating for tp throughput.
The repo convention is an immediate explicit pin::

    self._last_logits = self._last_logits.at[row].set(...)
    if self._shardings is not None:
        self._last_logits = jax.device_put(self._last_logits,
                                           self._shardings.logits)

This rule checks every assignment to a donated-carry attribute
(``self.cache``, ``self._pool_k`` ...).  The value is considered pinned
when it is:

* a call to a module-level **jitted** function (pins internally via
  ``with_sharding_constraint`` — that side is the jit's contract), also
  through tuple-unpack targets;
* a call carrying an explicit ``sharding=``/``shardings=`` kwarg
  (``init_cache(..., sharding=self._shardings.cache)``);
* ``jax.device_put(...)`` / ``with_sharding_constraint(...)`` — the pin
  itself;
* a plain name/attribute copy, ``None``/constant, or a conditional whose
  branches are each pinned.

Anything else is host-side compute and must be followed, later in the
same function, by a re-pin of the same attribute
(``self.<attr> = jax.device_put(self.<attr>, ...)``).  Unpinned
host-updated carries are findings.

Fires only on files that use the sharding plumbing (``_EngineShardings``/
``_shardings`` appears in the source) or under ``force_hot``.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from ray_tpu._private.lint.core import (
    FileContext,
    Finding,
    Rule,
    collect_jitted,
    dotted_name,
    register,
)

CARRY_ATTRS = frozenset({
    "cache",
    "_d_cache",
    "_last_logits",
    "_d_last_logits",
    "_pool_k",
    "_pool_v",
    "_pool_dk",
    "_pool_dv",
})

_PIN_TAILS = ("device_put", "with_sharding_constraint")
_SHARDING_KWARGS = ("sharding", "shardings", "out_shardings")


def _self_carry(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self" \
            and node.attr in CARRY_ATTRS:
        return node.attr
    return ""


@register
class ShardingPinRule(Rule):
    name = "sharding-pin"
    description = (
        "host-rebuilt donated jit carries must re-pin their sharding "
        "(device_put/with_sharding_constraint) before the next dispatch"
    )

    def check(self, ctx: FileContext) -> List[Finding]:
        if not ctx.config.force_hot and "_shardings" not in ctx.source:
            return []
        jitted = set(collect_jitted(ctx.tree))
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(ctx, node, jitted))
        return findings

    def _check_function(self, ctx: FileContext, fn: ast.AST,
                        jitted: set) -> List[Finding]:
        # attr -> line of a later `self.attr = device_put/wsc(...)` re-pin
        repin_lines: Dict[str, List[int]] = {}
        assigns: List[tuple] = []   # (lineno, node, attrs, value)
        for node in self._own_nodes(fn):
            if not isinstance(node, ast.Assign):
                continue
            attrs = []
            for tgt in node.targets:
                if isinstance(tgt, ast.Tuple):
                    attrs.extend(a for a in
                                 (_self_carry(e) for e in tgt.elts) if a)
                else:
                    a = _self_carry(tgt)
                    if a:
                        attrs.append(a)
            if not attrs:
                continue
            if self._is_pin_call(node.value):
                for a in attrs:
                    repin_lines.setdefault(a, []).append(node.lineno)
            assigns.append((node.lineno, node, attrs, node.value))
        out: List[Finding] = []
        for lineno, node, attrs, value in sorted(assigns,
                                                 key=lambda t: t[0]):
            if self._value_pinned(value, jitted):
                continue
            for attr in attrs:
                if any(l > lineno for l in repin_lines.get(attr, ())):
                    continue       # re-pinned later in this function
                out.append(ctx.finding(
                    self.name,
                    node,
                    f"`self.{attr}` is rebuilt on the host without a "
                    "sharding pin; follow with jax.device_put(self."
                    f"{attr}, self._shardings.*) (or produce it inside "
                    "jit) so the tp layout does not decay to replicated",
                ))
        return out

    # -- value classification ------------------------------------------------

    def _is_pin_call(self, value: ast.AST) -> bool:
        if isinstance(value, ast.Call):
            fn = dotted_name(value.func)
            return fn.split(".")[-1] in _PIN_TAILS
        return False

    def _value_pinned(self, value: ast.AST, jitted: set) -> bool:
        if isinstance(value, ast.Call):
            fn = dotted_name(value.func)
            tail = fn.split(".")[-1] if fn else ""
            if tail in _PIN_TAILS:
                return True
            if fn in jitted:
                return True
            if any(kw.arg in _SHARDING_KWARGS for kw in value.keywords
                   if kw.arg is not None):
                return True
            return False
        if isinstance(value, ast.IfExp):
            return self._value_pinned(value.body, jitted) and \
                self._value_pinned(value.orelse, jitted)
        if isinstance(value, (ast.Name, ast.Attribute)):
            return True            # plain move of an already-placed array
        if isinstance(value, ast.Constant):
            return True            # None / scalar sentinel
        return False

    @staticmethod
    def _own_nodes(fn: ast.AST):
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))
