"""graftlint core: AST-based static analysis for ray_tpu's serving hot path.

The serving PRs defend a handful of repo invariants (one host pull per decode
dispatch, guarded tracer spans, zero steady-state retraces, metric naming
conventions).  graftlint turns those invariants into machine-checked rules:

* a :class:`Rule` registry (``@register`` decorator, one module per rule),
* per-line suppression comments::

      something_deliberate()  # graftlint: disable=host-sync -- reason why

* a checked-in baseline (``baseline.json``) keyed by ``(rule, path, symbol)``
  so deliberate keeps survive line drift without re-triggering CI,
* text / JSON reporters shared by ``tools/graft_lint.py`` and the tier-1
  pytest gate (``tests/test_graft_lint.py::test_tree_is_clean``).

Rules receive a :class:`FileContext` (source, AST, parent links, suppression
table) and return :class:`Finding` objects; the runner marks findings landing
on a suppressed line and the reporters split open vs. suppressed.

See ``docs/lint.md`` for the rule catalogue and how to add a rule.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# Repo root (ray_tpu/_private/lint/core.py -> three parents up).
_REPO_ROOT = Path(__file__).resolve().parents[3]

_SUPPRESS_RE = re.compile(
    r"graftlint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(?P<reason>.*))?$"
)

_METRIC_NAME_RE = re.compile(r"^(llm_|serve_llm_)[a-z0-9_]+$")
_GLOSSARY_TOKEN_RE = re.compile(r"`((?:llm_|serve_llm_)[a-z0-9_*]+)`")


# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a specific source location.

    ``symbol`` is the dotted enclosing scope (``Class.method`` or function
    name, ``<module>`` at top level); the baseline keys on
    ``(rule, path, symbol)`` so entries survive unrelated line drift.
    """

    rule: str
    path: str
    line: int
    col: int
    message: str
    symbol: str = "<module>"
    suppressed: bool = False
    reason: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def to_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "symbol": self.symbol,
            "message": self.message,
            "suppressed": self.suppressed,
            "reason": self.reason,
        }

    def format(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LintConfig:
    """Knobs shared by all rules.

    ``force_hot`` treats every scanned file as hot-path (used by the synthetic
    fixture tests, which lint in-memory snippets with throwaway names).
    """

    hot_path_files: frozenset = frozenset(
        {"engine.py", "fleet.py", "generate.py", "speculative.py", "block_pool.py"}
    )
    # Files that own BlockPool handles (kv-refcount) / the dispatch ring
    # (flush-order) / donated sharded carries (sharding-pin).  The invariant
    # analyzers only fire where the invariant lives.
    kv_files: frozenset = frozenset({"engine.py", "prefix_cache.py", "block_pool.py",
                                     "adapter_pool.py"})
    host_sync_allowed_functions: frozenset = frozenset({"_device_get", "_emit_block"})
    metric_prefixes: Tuple[str, ...] = (
        "llm_engine_",
        "llm_fleet_",
        "llm_spec_",
        "serve_llm_",
    )
    glossary_path: Optional[Path] = None
    glossary: Optional[frozenset] = None
    force_hot: bool = False

    def is_hot_path(self, path: Path) -> bool:
        return self.force_hot or path.name in self.hot_path_files

    def is_kv_path(self, path: Path) -> bool:
        return self.force_hot or path.name in self.kv_files

    def metric_glossary(self) -> frozenset:
        if self.glossary is None:
            doc = self.glossary_path or (_REPO_ROOT / "docs" / "serving.md")
            entries: Set[str] = set()
            try:
                text = doc.read_text()
            except OSError:
                text = ""
            for match in _GLOSSARY_TOKEN_RE.finditer(text):
                entries.add(match.group(1))
            self.glossary = frozenset(entries)
        return self.glossary

    def glossary_has(self, name: str) -> bool:
        glossary = self.metric_glossary()
        if name in glossary:
            return True
        for entry in glossary:
            if "*" in entry and fnmatch.fnmatchcase(name, entry):
                return True
        return False

    def glossary_has_prefix(self, head: str) -> bool:
        """True if any glossary entry could complete a dynamic name ``head + ...``."""
        glossary = self.metric_glossary()
        for entry in glossary:
            if entry.startswith(head):
                return True
            if "*" in entry and fnmatch.fnmatchcase(head + "x", entry):
                return True
        return False


# ---------------------------------------------------------------------------
# rule registry
# ---------------------------------------------------------------------------


RULE_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator adding a Rule subclass to the global registry."""
    if not getattr(cls, "name", ""):
        raise ValueError(f"rule class {cls.__name__} has no name")
    RULE_REGISTRY[cls.name] = cls
    return cls


class Rule:
    """Base class for analyzers.  Subclasses set ``name``/``description`` and
    implement :meth:`check` returning findings for one file."""

    name = ""
    description = ""

    def check(self, ctx: "FileContext") -> List[Finding]:
        raise NotImplementedError


def default_rules(names: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instantiate registered rules (all analyzers import-registered)."""
    # Import for side effect: each module registers its rule class.
    from ray_tpu._private.lint import (  # noqa: F401
        rules_flush_order,
        rules_host_sync,
        rules_jit_hygiene,
        rules_kv_refcount,
        rules_metrics_name,
        rules_sharding_pin,
        rules_trace_guard,
    )

    if names:
        unknown = [n for n in names if n not in RULE_REGISTRY]
        if unknown:
            raise KeyError(f"unknown rule(s): {', '.join(unknown)}")
        selected = [RULE_REGISTRY[n] for n in names]
    else:
        selected = [RULE_REGISTRY[n] for n in sorted(RULE_REGISTRY)]
    return [cls() for cls in selected]


@register
class SuppressionSyntaxRule(Rule):
    """Malformed ``# graftlint: disable=...`` directives are findings, not
    silent no-ops: a missing ``-- reason`` makes the directive inert, and an
    unknown rule name means the keep guards nothing."""

    name = "suppression-syntax"
    description = (
        "graftlint directives need known rule names and a '-- reason'; "
        "malformed directives are inert and flagged"
    )

    def check(self, ctx: "FileContext") -> List[Finding]:
        findings: List[Finding] = []
        for line, col, rules, problem in ctx.suppression_issues:
            names = ",".join(sorted(rules)) or "?"
            findings.append(
                Finding(
                    rule=self.name,
                    path=ctx.rel,
                    line=line,
                    col=col,
                    message=(
                        f"malformed suppression (disable={names}): {problem}; "
                        "directive has no effect"
                    ),
                    symbol=ctx.symbol_at_line(line),
                )
            )
        for line, (rules, _reason) in sorted(ctx.suppressions.items()):
            unknown = sorted(
                r for r in rules if r != "*" and r not in RULE_REGISTRY
            )
            if unknown:
                findings.append(
                    Finding(
                        rule=self.name,
                        path=ctx.rel,
                        line=line,
                        col=0,
                        message=(
                            "unknown rule name(s) in suppression: "
                            + ", ".join(unknown)
                        ),
                        symbol=ctx.symbol_at_line(line),
                    )
                )
        return findings


# ---------------------------------------------------------------------------
# per-file context
# ---------------------------------------------------------------------------


class FileContext:
    """Parsed source plus the derived tables every rule needs: parent links,
    enclosing-scope lookup, and the per-line suppression map."""

    def __init__(self, path: Path, source: str, config: LintConfig):
        self.path = path
        self.rel = _relpath(path)
        self.source = source
        self.config = config
        self.tree = ast.parse(source, filename=str(path))
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        # line -> (set of rule names or {"*"}, reason); malformed directives
        # (missing `-- reason`) are inert and land in suppression_issues.
        self.suppressions, self.suppression_issues = _parse_suppressions(source)
        self._summaries = None

    @property
    def summaries(self):
        """Lazy :class:`~.dataflow.ModuleSummaries` for this file — the
        interprocedural rules share one function table + summary cache.
        Imported lazily: dataflow depends on core's helpers."""
        if self._summaries is None:
            from ray_tpu._private.lint.dataflow import ModuleSummaries

            self._summaries = ModuleSummaries(
                self.tree,
                sync_exempt=self.config.host_sync_allowed_functions,
            )
        return self._summaries

    def symbol_at(self, node: ast.AST) -> str:
        names: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                names.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(names)) if names else "<module>"

    def symbol_at_line(self, line: int) -> str:
        """Dotted scope covering a physical line (deepest def/class whose
        span contains it) — for findings that anchor to comments rather
        than AST nodes."""
        best: Optional[ast.AST] = None
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                end = getattr(node, "end_lineno", node.lineno)
                if node.lineno <= line <= end:
                    if best is None or node.lineno >= best.lineno:
                        best = node
        return self.symbol_at(best) if best is not None else "<module>"

    def enclosing_function(
        self, node: ast.AST
    ) -> Optional[ast.FunctionDef]:
        cur: Optional[ast.AST] = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def finding(
        self, rule: str, node: ast.AST, message: str
    ) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(
            rule=rule,
            path=self.rel,
            line=line,
            col=col,
            message=message,
            symbol=self.symbol_at(node),
        )


def _relpath(path: Path) -> str:
    try:
        return path.resolve().relative_to(_REPO_ROOT).as_posix()
    except ValueError:
        return path.as_posix()


def _parse_suppressions(
    source: str,
) -> Tuple[Dict[int, Tuple[Set[str], str]], List[Tuple[int, int, Set[str], str]]]:
    """Parse ``# graftlint: disable=rule[,rule...] -- reason`` directives.

    Returns ``(table, issues)``:

    * ``table``: physical line -> (suppressed rule names, reason) for
      well-formed directives.  Multi-rule lists split on commas;
      ``disable=all`` (or ``*``) suppresses every rule on that line.
    * ``issues``: ``(line, col, rules, problem)`` for malformed directives.
      A directive with no ``-- reason`` is **inert** (it suppresses
      nothing) and is reported by the ``suppression-syntax`` rule instead
      of being silently honoured or silently dropped.

    Uses the tokenizer so string literals containing ``graftlint:`` are
    never mistaken for directives.
    """
    table: Dict[int, Tuple[Set[str], str]] = {}
    issues: List[Tuple[int, int, Set[str], str]] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            match = _SUPPRESS_RE.search(tok.string)
            if not match:
                if "graftlint:" in tok.string and "disable" in tok.string:
                    issues.append(
                        (tok.start[0], tok.start[1], set(),
                         "unparseable graftlint directive")
                    )
                continue
            rules = {r.strip() for r in match.group(1).split(",") if r.strip()}
            if "all" in rules or "*" in rules:
                rules = {"*"}
            reason = (match.group("reason") or "").strip()
            if match.group("reason") is None or not reason:
                issues.append(
                    (tok.start[0], tok.start[1], rules, "missing '-- reason'")
                )
                continue  # inert: a keep without a why is not a keep
            table[tok.start[0]] = (rules, reason)
    except tokenize.TokenError:
        pass
    return table, issues


# ---------------------------------------------------------------------------
# shared AST helpers (used by several rules)
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """``jax.jit`` -> "jax.jit"; "" when the expression is not a pure dotted
    name (calls, subscripts, ...)."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def root_name(node: ast.AST) -> str:
    """Leftmost Name of an attribute/subscript chain (``self.cache[i]`` -> "self")."""
    cur = node
    while isinstance(cur, (ast.Attribute, ast.Subscript)):
        cur = cur.value
    if isinstance(cur, ast.Name):
        return cur.id
    return ""


def expr_text(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


@dataclasses.dataclass
class JitInfo:
    """Signature facts for one module-level jitted function."""

    name: str
    lineno: int
    params: List[str]
    static_names: Set[str]
    donate_names: Set[str]
    donate_positions: Set[int]


def _str_elements(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.add(elt.value)
    return out


def _int_elements(node: ast.AST) -> Set[int]:
    out: Set[int] = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.add(elt.value)
    return out


def _jit_kwargs(call: ast.Call) -> Tuple[Set[str], Set[str], Set[int]]:
    static: Set[str] = set()
    donate_names: Set[str] = set()
    donate_pos: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            static |= _str_elements(kw.value)
        elif kw.arg == "donate_argnames":
            donate_names |= _str_elements(kw.value)
        elif kw.arg == "donate_argnums":
            donate_pos |= _int_elements(kw.value)
        elif kw.arg == "static_argnums":
            # positional statics are resolved against params by the caller
            donate_pos  # no-op; kept explicit for symmetry
    return static, donate_names, donate_pos


def _is_jit_call(call: ast.Call) -> bool:
    """True for ``jax.jit(...)`` and ``functools.partial(jax.jit, ...)``."""
    fn = dotted_name(call.func)
    if fn in ("jax.jit", "jit"):
        return True
    if fn in ("functools.partial", "partial") and call.args:
        return dotted_name(call.args[0]) in ("jax.jit", "jit")
    return False


def collect_jitted(tree: ast.Module) -> Dict[str, JitInfo]:
    """Module-level jitted functions: decorated defs and ``f = jax.jit(g, ...)``
    style assignments.  Returns name -> JitInfo."""
    infos: Dict[str, JitInfo] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for deco in node.decorator_list:
                if isinstance(deco, ast.Call) and _is_jit_call(deco):
                    static, dnames, dpos = _jit_kwargs(deco)
                elif dotted_name(deco) in ("jax.jit", "jit"):
                    static, dnames, dpos = set(), set(), set()
                else:
                    continue
                params = [a.arg for a in node.args.args]
                infos[node.name] = JitInfo(
                    name=node.name,
                    lineno=node.lineno,
                    params=params,
                    static_names=static,
                    donate_names=dnames,
                    donate_positions=dpos,
                )
                break
        elif isinstance(node, ast.Assign):
            value = node.value
            if isinstance(value, ast.Call) and _is_jit_call(value):
                static, dnames, dpos = _jit_kwargs(value)
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        infos[target.id] = JitInfo(
                            name=target.id,
                            lineno=node.lineno,
                            params=[],
                            static_names=static,
                            donate_names=dnames,
                            donate_positions=dpos,
                        )
    return infos


# ---------------------------------------------------------------------------
# runner + report
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LintReport:
    findings: List[Finding]
    files_scanned: int
    errors: List[str] = dataclasses.field(default_factory=list)

    @property
    def open(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def to_dict(self) -> Dict[str, object]:
        return {
            "files_scanned": self.files_scanned,
            "open_count": len(self.open),
            "suppressed_count": len(self.suppressed),
            "errors": list(self.errors),
            "findings": [f.to_dict() for f in self.findings],
        }

    def format_text(self, show_suppressed: bool = False) -> str:
        lines: List[str] = []
        for f in sorted(self.findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
            if f.suppressed and not show_suppressed:
                continue
            lines.append(f.format())
        for err in self.errors:
            lines.append(f"error: {err}")
        lines.append(
            f"{self.files_scanned} file(s) scanned, {len(self.open)} open finding(s), "
            f"{len(self.suppressed)} suppressed"
        )
        return "\n".join(lines)


def _apply_suppressions(ctx: FileContext, findings: List[Finding]) -> List[Finding]:
    out: List[Finding] = []
    for f in findings:
        entry = ctx.suppressions.get(f.line)
        if entry is not None and ("*" in entry[0] or f.rule in entry[0]):
            f = dataclasses.replace(f, suppressed=True, reason=entry[1])
        out.append(f)
    return out


def lint_source(
    source: str,
    path: str = "<memory>.py",
    config: Optional[LintConfig] = None,
    rules: Optional[Sequence[Rule]] = None,
) -> List[Finding]:
    """Lint an in-memory snippet (the fixture-test entry point)."""
    config = config or LintConfig()
    rules = list(rules) if rules is not None else default_rules()
    ctx = FileContext(Path(path), source, config)
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    return _apply_suppressions(ctx, findings)


def iter_python_files(paths: Sequence[Path]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            files.append(p)
    return files


def lint_paths(
    paths: Sequence[Path],
    config: Optional[LintConfig] = None,
    rules: Optional[Sequence[Rule]] = None,
) -> LintReport:
    config = config or LintConfig()
    rules = list(rules) if rules is not None else default_rules()
    findings: List[Finding] = []
    errors: List[str] = []
    files = iter_python_files(paths)
    for path in files:
        try:
            source = path.read_text()
            ctx = FileContext(path, source, config)
        except (OSError, SyntaxError, ValueError) as exc:
            errors.append(f"{path}: {exc}")
            continue
        file_findings: List[Finding] = []
        for rule in rules:
            file_findings.extend(rule.check(ctx))
        findings.extend(_apply_suppressions(ctx, file_findings))
    return LintReport(findings=findings, files_scanned=len(files), errors=errors)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def baseline_entries(report: LintReport) -> List[Dict[str, object]]:
    """Aggregate *suppressed* findings into stable baseline entries."""
    counts: Dict[Tuple[str, str, str], Dict[str, object]] = {}
    for f in report.suppressed:
        entry = counts.setdefault(
            f.key(),
            {"rule": f.rule, "path": f.path, "symbol": f.symbol, "count": 0, "reason": f.reason},
        )
        entry["count"] = int(entry["count"]) + 1
        if f.reason and not entry["reason"]:
            entry["reason"] = f.reason
    return sorted(
        counts.values(), key=lambda e: (str(e["path"]), str(e["rule"]), str(e["symbol"]))
    )


def load_baseline(path: Path = DEFAULT_BASELINE) -> List[Dict[str, object]]:
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return []
    return list(data.get("suppressions", []))


def save_baseline(report: LintReport, path: Path = DEFAULT_BASELINE) -> None:
    payload = {
        "comment": "graftlint baseline: deliberate, inline-suppressed findings. "
        "Regenerate with tools/graft_lint.py --update-baseline.",
        "suppressions": baseline_entries(report),
    }
    Path(path).write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")


def diff_baseline(
    report: LintReport, baseline: List[Dict[str, object]]
) -> List[str]:
    """Human-readable drift between current suppressions and the baseline."""
    current = {
        (str(e["rule"]), str(e["path"]), str(e["symbol"])): int(e["count"])
        for e in baseline_entries(report)
    }
    recorded = {
        (str(e["rule"]), str(e["path"]), str(e["symbol"])): int(e.get("count", 0))
        for e in baseline
    }
    msgs: List[str] = []
    for key in sorted(set(current) | set(recorded)):
        cur, rec = current.get(key, 0), recorded.get(key, 0)
        if cur != rec:
            rule, path, symbol = key
            msgs.append(
                f"baseline drift: {rule} in {path}:{symbol} "
                f"(baseline {rec}, tree {cur}) -- run tools/graft_lint.py --update-baseline"
            )
    return msgs
