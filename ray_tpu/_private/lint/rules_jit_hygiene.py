"""jit-hygiene: retrace and donation hazards at jitted call sites.

Three mechanical hazards around ``jax.jit`` that have bitten serving PRs:

1. **jit-in-loop** — constructing a jit wrapper inside a ``for``/``while``
   body creates a fresh cache per iteration and recompiles every call.
2. **donated-buffer reuse** — reading a buffer after passing it to a donated
   parameter (``donate_argnames``/``donate_argnums``) is undefined once XLA
   aliases the storage; the engine convention is to rebind the result over
   the donated expression on the same statement
   (``self.cache, ... = _decode_multi(self.params, self.cache, ...)``).
3. **static-varying scalar** — passing an obviously per-call-varying Python
   scalar (a ``len(...)``, ``.shape[...]`` access, or an enclosing loop
   variable) as a *static* jit arg keys a new compile per distinct value.

The rule resolves module-level jitted functions (decorated with ``jax.jit`` /
``functools.partial(jax.jit, ...)`` or bound via ``f = jax.jit(g, ...)``) and
checks their call sites.  Calls using ``*args`` splats skip the positional
donation/static mapping (alignment is unknowable statically).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.lint.core import (
    FileContext,
    Finding,
    JitInfo,
    Rule,
    collect_jitted,
    dotted_name,
    expr_text,
    register,
)


@register
class JitHygieneRule(Rule):
    name = "jit-hygiene"
    description = "retrace/donation hazards at jax.jit call sites"

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        jitted = collect_jitted(ctx.tree)
        findings.extend(self._check_jit_in_loop(ctx))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            # method-style tails (self._decode = jax.jit(...) then
            # self._decode(...)) resolve on the final component.
            tail = name.rsplit(".", 1)[-1] if name else ""
            info = jitted.get(name) or jitted.get(tail)
            if info is None:
                continue
            has_splat = any(isinstance(a, ast.Starred) for a in node.args)
            findings.extend(self._check_donated_reuse(ctx, node, info, has_splat))
            findings.extend(self._check_static_varying(ctx, node, info, has_splat))
        return findings

    # -- (1) jit() constructed inside a loop body ---------------------------

    def _check_jit_in_loop(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if dotted_name(node.func) not in ("jax.jit", "jit"):
                continue
            for anc in ctx.ancestors(node):
                if isinstance(anc, (ast.For, ast.While)):
                    findings.append(
                        ctx.finding(
                            self.name,
                            node,
                            "jax.jit(...) constructed inside a loop body builds "
                            "a fresh compile cache per iteration; hoist the "
                            "jitted function to module scope",
                        )
                    )
                    break
                if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break  # nested defs reset the loop context
        return findings

    # -- donated/static argument mapping ------------------------------------

    def _bound_args(
        self, call: ast.Call, info: JitInfo, has_splat: bool
    ) -> List[Tuple[str, Optional[int], ast.expr]]:
        """(param_name_or_"", positional_index_or_None, expr) per call arg."""
        bound: List[Tuple[str, Optional[int], ast.expr]] = []
        if not has_splat:
            for idx, arg in enumerate(call.args):
                pname = info.params[idx] if idx < len(info.params) else ""
                bound.append((pname, idx, arg))
        for kw in call.keywords:
            if kw.arg is not None:
                bound.append((kw.arg, None, kw.value))
        return bound

    def _check_donated_reuse(
        self, ctx: FileContext, call: ast.Call, info: JitInfo, has_splat: bool
    ) -> List[Finding]:
        if not (info.donate_names or info.donate_positions):
            return []
        donated: List[ast.expr] = []
        for pname, idx, arg in self._bound_args(call, info, has_splat):
            if (pname and pname in info.donate_names) or (
                idx is not None and idx in info.donate_positions
            ):
                donated.append(arg)
        fn = ctx.enclosing_function(call)
        if fn is None or not donated:
            return []
        findings: List[Finding] = []
        call_line = getattr(call, "end_lineno", call.lineno)
        for arg in donated:
            if not isinstance(arg, (ast.Name, ast.Attribute)):
                continue
            text = expr_text(arg)
            if not text:
                continue
            reuse = self._first_reuse(fn, text, call.lineno, call_line)
            if reuse is not None:
                findings.append(
                    ctx.finding(
                        self.name,
                        reuse,
                        f"`{text}` was donated to `{info.name}` on line "
                        f"{call.lineno} and is read afterwards; XLA may have "
                        "aliased its buffer — rebind the jit result first",
                    )
                )
        return findings

    def _first_reuse(
        self, fn: ast.FunctionDef, text: str, call_start: int, call_end: int
    ) -> Optional[ast.AST]:
        """First Load of `text` after the call with no intervening rebind.

        The sanctioned pattern rebinds the jit result over the donated
        expression on the call statement itself (a Store at ``call_start``),
        which clears all later loads.
        """
        loads: List[ast.AST] = []
        stores: List[int] = []
        for node in ast.walk(fn):
            if isinstance(node, (ast.Name, ast.Attribute)) and expr_text(node) == text:
                c = getattr(node, "ctx", None)
                if isinstance(c, ast.Store):
                    stores.append(node.lineno)
                elif isinstance(c, ast.Load):
                    loads.append(node)
        for load in sorted(loads, key=lambda n: (n.lineno, n.col_offset)):
            if load.lineno <= call_end:
                continue
            if any(call_start <= s <= load.lineno for s in stores):
                return None
            return load
        return None

    # -- (3) varying python scalar into a static parameter ------------------

    def _check_static_varying(
        self, ctx: FileContext, call: ast.Call, info: JitInfo, has_splat: bool
    ) -> List[Finding]:
        if not info.static_names:
            return []
        loop_vars = self._enclosing_loop_vars(ctx, call)
        one_hop = self._local_assignments(ctx, call)
        findings: List[Finding] = []
        for pname, _idx, arg in self._bound_args(call, info, has_splat):
            if pname not in info.static_names:
                continue
            exprs = [arg]
            if isinstance(arg, ast.Name) and arg.id in one_hop:
                exprs.append(one_hop[arg.id])
            for expr in exprs:
                hazard = self._varying_reason(expr, loop_vars)
                if hazard:
                    findings.append(
                        ctx.finding(
                            self.name,
                            arg,
                            f"static jit arg `{pname}` of `{info.name}` is fed "
                            f"a per-call-varying value ({hazard}); every "
                            "distinct value triggers a recompile",
                        )
                    )
                    break
        return findings

    def _varying_reason(self, expr: ast.AST, loop_vars: Set[str]) -> str:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                fn = dotted_name(node.func)
                if fn == "len":
                    return "len(...)"
            if isinstance(node, ast.Attribute) and node.attr == "shape":
                return ".shape access"
            if isinstance(node, ast.Name) and node.id in loop_vars:
                return f"loop variable `{node.id}`"
        return ""

    def _enclosing_loop_vars(self, ctx: FileContext, call: ast.Call) -> Set[str]:
        out: Set[str] = set()
        for anc in ctx.ancestors(call):
            if isinstance(anc, ast.For):
                for node in ast.walk(anc.target):
                    if isinstance(node, ast.Name):
                        out.add(node.id)
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
        return out

    def _local_assignments(
        self, ctx: FileContext, call: ast.Call
    ) -> Dict[str, ast.expr]:
        """name -> last assigned expression before the call, one hop only."""
        fn = ctx.enclosing_function(call)
        out: Dict[str, ast.expr] = {}
        if fn is None:
            return out
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if getattr(node, "lineno", 0) >= call.lineno:
                continue
            for target in node.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = node.value
        return out
