"""metrics-name: naming conventions + glossary coverage for serving metrics.

Every metric emitted through ``ray_tpu.util.metrics`` follows the serving
naming conventions (``llm_engine_*``, ``llm_fleet_*``, ``llm_spec_*``,
``serve_llm_*``) and must appear in the docs/serving.md glossary (exact name
or a documented wildcard like ``llm_engine_kv_*``) so dashboards never chase
undocumented names.

The rule scans string literals whose *entire* value is shaped like a metric
name (``^(llm_|serve_llm_)[a-z0-9_]+$``) wherever they appear — constructor
args, dict keys, one-hop ``name = "..."`` locals — plus f-strings whose
leading literal matches the prefix (``f"llm_fleet_{field}"``; validated
against glossary entries that can complete the dynamic tail).  Docstrings are
exempt.  Strings that merely *look* like metric names but are not
(deployment ids etc.) carry an inline suppression.
"""

from __future__ import annotations

import ast
from typing import List

from ray_tpu._private.lint.core import (
    _METRIC_NAME_RE,
    FileContext,
    Finding,
    Rule,
    register,
)


def _is_docstring(ctx: FileContext, node: ast.Constant) -> bool:
    parent = ctx.parents.get(node)
    if not isinstance(parent, ast.Expr):
        return False
    grand = ctx.parents.get(parent)
    return isinstance(
        grand, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
    )


def _in_dunder_all(ctx: FileContext, node: ast.Constant) -> bool:
    """Strings inside ``__all__ = [...]`` are identifiers, not metrics."""
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.Assign):
            return any(
                isinstance(t, ast.Name) and t.id == "__all__" for t in anc.targets
            )
    return False


def _is_prefix_context(ctx: FileContext, node: ast.Constant) -> bool:
    """True when the literal is a metric-name *head*: the value of a
    ``prefix=`` keyword or the default of a parameter named ``prefix``
    (``report_engine_stats(stats, prefix="serve_llm_fleet")``)."""
    parent = ctx.parents.get(node)
    if isinstance(parent, ast.keyword) and parent.arg == "prefix":
        return True
    if isinstance(parent, ast.arguments):
        defaults = parent.defaults
        if node in defaults:
            pos_args = parent.args[-len(defaults):] if defaults else []
            idx = defaults.index(node)
            if idx < len(pos_args) and pos_args[idx].arg == "prefix":
                return True
        for arg, default in zip(parent.kwonlyargs, parent.kw_defaults):
            if default is node and arg.arg == "prefix":
                return True
    return False


@register
class MetricsNameRule(Rule):
    name = "metrics-name"
    description = (
        "metric names must follow llm_engine_*/llm_fleet_*/llm_spec_*/"
        "serve_llm_* conventions and appear in the docs/serving.md glossary"
    )

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        prefixes = ctx.config.metric_prefixes
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                value = node.value
                if not _METRIC_NAME_RE.match(value):
                    continue
                if _is_docstring(ctx, node) or _in_dunder_all(ctx, node):
                    continue
                if isinstance(ctx.parents.get(node), ast.JoinedStr):
                    continue  # f-string heads are handled below
                if _is_prefix_context(ctx, node):
                    head = value if value.endswith("_") else value + "_"
                    if not head.startswith(prefixes):
                        findings.append(
                            ctx.finding(
                                self.name,
                                node,
                                f'metric prefix "{value}" does not use a '
                                f"convention prefix ({', '.join(prefixes)})",
                            )
                        )
                    elif not ctx.config.glossary_has_prefix(head):
                        findings.append(
                            ctx.finding(
                                self.name,
                                node,
                                f'metric prefix "{value}" has no glossary entry '
                                "starting with that head; document the family "
                                f'(e.g. a "{head}*" wildcard)',
                            )
                        )
                    continue
                if not value.startswith(prefixes):
                    findings.append(
                        ctx.finding(
                            self.name,
                            node,
                            f'metric-shaped name "{value}" does not use a '
                            f"convention prefix ({', '.join(prefixes)})",
                        )
                    )
                elif not ctx.config.glossary_has(value):
                    findings.append(
                        ctx.finding(
                            self.name,
                            node,
                            f'metric "{value}" is not in the docs/serving.md '
                            "glossary; document it (or a covering wildcard)",
                        )
                    )
            elif isinstance(node, ast.JoinedStr):
                findings.extend(self._check_fstring(ctx, node, prefixes))
        return findings

    def _check_fstring(
        self, ctx: FileContext, node: ast.JoinedStr, prefixes
    ) -> List[Finding]:
        if not node.values:
            return []
        head = node.values[0]
        if not (isinstance(head, ast.Constant) and isinstance(head.value, str)):
            return []
        text = head.value
        if not (text.startswith("llm_") or text.startswith("serve_llm_")):
            return []
        if not _METRIC_NAME_RE.match(text):
            return []
        if not text.startswith(prefixes):
            return [
                ctx.finding(
                    self.name,
                    node,
                    f'dynamic metric name head "{text}..." does not use a '
                    f"convention prefix ({', '.join(prefixes)})",
                )
            ]
        if not ctx.config.glossary_has_prefix(text):
            return [
                ctx.finding(
                    self.name,
                    node,
                    f'dynamic metric name "{text}{{...}}" has no glossary '
                    "entry starting with that head; add one (wildcards like "
                    f'"{text}*" count)',
                )
            ]
        return []
