"""trace-guard: every tracer span emission must sit behind ``trace.enabled``.

The PR 9 tracing convention keeps the null-tracer decode path allocation-free
by guarding every span call site::

    if self.trace.enabled:
        self.trace.add("decode.dispatch", t0, tr.now())

    t0 = tr.now() if tr.enabled else 0.0

    if etr is None or not etr.enabled:
        return
    etr.add(...)

An unguarded emission pays attribute lookups, float math and (for real
tracers) list appends on every decode step even when tracing is off — the
exact overhead the ``test_gate_null_tracer_zero_allocations_on_decode_path``
perf gate exists to prevent.

The rule matches calls of span methods (``add``/``instant``/``open``/
``close``/``mark``/``span_since_mark``/``now``/``finish``) on receivers that
look like tracers (``tr``, ``tracer``, ``*.trace``, ``*_tracer`` ...) and
checks for an ``.enabled`` test in an ancestor ``if``/ternary/``and`` chain or
an earlier early-return guard in the same function.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ray_tpu._private.lint.core import FileContext, Finding, Rule, expr_text, register

_SPAN_METHODS = {
    "add",
    "instant",
    "open",
    "close",
    "mark",
    "span_since_mark",
    "now",
    "finish",
}

_TRACER_NAMES = {"tr", "tracer", "etr", "trace"}


def _is_tracer_receiver(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id in _TRACER_NAMES or "trace" in node.id
    if isinstance(node, ast.Attribute):
        attr = node.attr
        return (
            attr in ("trace", "tracer")
            or attr.endswith("_trace")
            or attr.endswith("_tracer")
        )
    return False


def _mentions_enabled(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "enabled":
            return True
        if isinstance(sub, ast.Name) and sub.id == "enabled":
            return True
    return False


@register
class TraceGuardRule(Rule):
    name = "trace-guard"
    description = "tracer span emitted without a trace.enabled guard"

    def check(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr not in _SPAN_METHODS:
                continue
            if not _is_tracer_receiver(func.value):
                continue
            if self._is_guarded(ctx, node):
                continue
            findings.append(
                ctx.finding(
                    self.name,
                    node,
                    f"tracer span `{expr_text(func)}(...)` emitted without a "
                    "`.enabled` guard (wrap in `if trace.enabled:` or an "
                    "early-return guard)",
                )
            )
        return findings

    def _is_guarded(self, ctx: FileContext, call: ast.Call) -> bool:
        # (1) ancestor if / while / ternary / boolop testing .enabled
        prev: ast.AST = call
        for anc in ctx.ancestors(call):
            if isinstance(anc, (ast.If, ast.While)) and _mentions_enabled(anc.test):
                return True
            if isinstance(anc, ast.IfExp) and _mentions_enabled(anc.test):
                return True
            if isinstance(anc, ast.BoolOp) and isinstance(anc.op, ast.And):
                # `tr.enabled and tr.add(...)` — guard must precede the call
                for value in anc.values:
                    if value is prev:
                        break
                    if _mentions_enabled(value):
                        return True
            if isinstance(anc, ast.Assert) and _mentions_enabled(anc.test):
                return True
            prev = anc
        # (2) earlier early-return guard in the enclosing function:
        #     if tr is None or not tr.enabled: return
        fn = ctx.enclosing_function(call)
        if fn is not None:
            for stmt in fn.body:
                if stmt.lineno >= call.lineno:
                    break
                if (
                    isinstance(stmt, ast.If)
                    and _mentions_enabled(stmt.test)
                    and stmt.body
                    and isinstance(stmt.body[-1], (ast.Return, ast.Raise, ast.Continue))
                ):
                    return True
        return False
