"""host-sync: implicit device->host transfers in hot-path modules.

The serving invariant since PR 5 is *one* blocking host pull per decode
dispatch, routed through the ``_device_get`` choke point so the engine can
account bytes and the runtime sanitizer can mark the pull expected.  Anything
else that forces a sync on the hot path — ``.item()``, ``float()/int()/bool()``
on a jax value, ``np.asarray`` on a device array, truthiness branching on an
array — stalls the dispatch ring and silently serialises the pipeline.

Detection is a per-function taint pass: values are "device" tainted when they
come from a ``jnp.*``/``jax.*`` expression or from a call to a module-level
jitted function, and taint propagates through assignments, tuple unpacking,
arithmetic, subscripts and method calls.  Sync-forcing operations on tainted
values are findings.  Functions on the whitelist (``_device_get``,
``_emit_block``) are the sanctioned choke points and are skipped.

v2 makes the pass **interprocedural** (one summary level, via
:mod:`.dataflow`): a call to a module-local helper whose summary says
``returns_device`` taints the call result even when the helper's ``jnp``
roots are out of view, and passing a tainted value to a helper whose
summary says it *syncs* that parameter (``sync_params``) is reported at the
call site — the sync happens one frame down, but the hot-path caller is the
code that has to change.  Suppressions that only existed because the old
analyzer could not follow a helper call are now either real findings or
deletable.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ray_tpu._private.lint.core import (
    FileContext,
    Finding,
    Rule,
    collect_jitted,
    register,
    root_name,
)

_SYNC_BUILTINS = {"float", "int", "bool"}
_SYNC_METHODS = {"item", "tolist"}
_NP_SYNC_FUNCS = {
    "np.asarray",
    "np.array",
    "np.ascontiguousarray",
    "numpy.asarray",
    "numpy.array",
    "numpy.ascontiguousarray",
}


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


class _Taint:
    """Tracks which local names hold device values inside one function.

    When constructed with module ``summaries`` (and the enclosing
    function's info as ``scope``), calls into module-local helpers whose
    summary says ``returns_device`` are tainted too — one level of
    interprocedural propagation."""

    def __init__(self, jitted: Set[str], summaries=None, scope=None):
        self.jitted = jitted
        self.summaries = summaries
        self.scope = scope
        self.names: Set[str] = set()

    def expr(self, node: ast.AST) -> bool:
        """Is this expression device-tainted?"""
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            # array metadata lives on the host; reading it never syncs
            if node.attr in ("shape", "ndim", "dtype", "size", "nbytes",
                            "sharding", "device", "itemsize"):
                return False
            return self.expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr(node.value)
        if isinstance(node, ast.Call):
            fn = _dotted(node.func)
            root = fn.split(".", 1)[0] if fn else ""
            if root in ("jnp", "jax", "lax"):
                return True
            if fn in self.jitted:
                return True
            # method call on a tainted receiver (x.astype(...), x.reshape(...))
            if isinstance(node.func, ast.Attribute) and self.expr(node.func.value):
                return True
            if self.summaries is not None:
                callee = self.summaries.resolve_call(node, self.scope)
                if callee is not None and self.summaries.returns_device(callee):
                    return True
            return False
        if isinstance(node, ast.BinOp):
            return self.expr(node.left) or self.expr(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.expr(node.operand)
        if isinstance(node, ast.Compare):
            return self.expr(node.left) or any(self.expr(c) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self.expr(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.expr(node.body) or self.expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr(node.value)
        return False

    def assign(self, target: ast.AST, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.names.add(target.id)
            else:
                self.names.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.assign(elt, tainted)
        elif isinstance(target, ast.Starred):
            self.assign(target.value, tainted)
        # Attribute / Subscript targets (self.cache = ...) are not tracked:
        # attribute taint would need whole-object analysis and the hot-path
        # rules below only fire on locally provable device values.


@register
class HostSyncRule(Rule):
    name = "host-sync"
    description = (
        "implicit device->host sync on a hot-path module outside the "
        "_device_get/_emit_block choke points"
    )

    def check(self, ctx: FileContext) -> List[Finding]:
        if not ctx.config.is_hot_path(ctx.path):
            return []
        jitted = set(collect_jitted(ctx.tree))
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in ctx.config.host_sync_allowed_functions:
                continue
            findings.extend(self._check_function(ctx, node, jitted))
        return findings

    def _check_function(
        self, ctx: FileContext, fn: ast.FunctionDef, jitted: Set[str]
    ) -> List[Finding]:
        summaries = ctx.summaries
        taint = _Taint(jitted, summaries=summaries,
                       scope=summaries.info_for(fn))
        findings: Dict[tuple, Finding] = {}
        # Two passes: the first only builds taint (so loop-carried values seen
        # late in the body taint their uses earlier in the next iteration),
        # the second reports.
        for report in (False, True):
            self._walk_body(ctx, fn.body, taint, findings if report else None)
        return list(findings.values())

    # -- statement walk (source order so taint respects def-before-use) -----

    def _walk_body(self, ctx, body, taint, findings) -> None:
        for stmt in body:
            self._walk_stmt(ctx, stmt, taint, findings)

    def _walk_stmt(self, ctx, stmt, taint, findings) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested scopes are visited independently
        if isinstance(stmt, ast.Assign):
            self._scan_expr(ctx, stmt.value, taint, findings)
            tainted = taint.expr(stmt.value)
            for target in stmt.targets:
                taint.assign(target, tainted)
            return
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._scan_expr(ctx, stmt.value, taint, findings)
            taint.assign(stmt.target, taint.expr(stmt.value))
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_expr(ctx, stmt.value, taint, findings)
            if taint.expr(stmt.value):
                taint.assign(stmt.target, True)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._check_truthiness(ctx, stmt.test, taint, findings)
            self._scan_expr(ctx, stmt.test, taint, findings)
            self._walk_body(ctx, stmt.body, taint, findings)
            self._walk_body(ctx, stmt.orelse, taint, findings)
            return
        if isinstance(stmt, ast.For):
            self._scan_expr(ctx, stmt.iter, taint, findings)
            taint.assign(stmt.target, taint.expr(stmt.iter))
            self._walk_body(ctx, stmt.body, taint, findings)
            self._walk_body(ctx, stmt.orelse, taint, findings)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._scan_expr(ctx, item.context_expr, taint, findings)
            self._walk_body(ctx, stmt.body, taint, findings)
            return
        if isinstance(stmt, ast.Try):
            self._walk_body(ctx, stmt.body, taint, findings)
            for handler in stmt.handlers:
                self._walk_body(ctx, handler.body, taint, findings)
            self._walk_body(ctx, stmt.orelse, taint, findings)
            self._walk_body(ctx, stmt.finalbody, taint, findings)
            return
        if isinstance(stmt, ast.Assert):
            self._check_truthiness(ctx, stmt.test, taint, findings)
            self._scan_expr(ctx, stmt.test, taint, findings)
            return
        # Return / Expr / Raise / Delete / Global / ... : scan expressions.
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(ctx, child, taint, findings)

    # -- expression scan ----------------------------------------------------

    def _scan_expr(self, ctx, expr, taint, findings) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._check_call(ctx, node, taint, findings)
            elif isinstance(node, ast.IfExp):
                self._check_truthiness(ctx, node.test, taint, findings)

    def _check_call(self, ctx, call: ast.Call, taint, findings) -> None:
        fn = _dotted(call.func)
        if fn in ("jax.device_get", "jax.block_until_ready"):
            self._emit(
                ctx,
                call,
                findings,
                f"`{fn}` blocks on a device->host transfer on the hot path; "
                "route the pull through _device_get",
            )
            return
        if fn in _NP_SYNC_FUNCS and call.args and taint.expr(call.args[0]):
            self._emit(
                ctx,
                call,
                findings,
                f"`{fn}` on a device value forces an implicit device->host "
                "transfer; route the pull through _device_get",
            )
            return
        if (
            isinstance(call.func, ast.Name)
            and call.func.id in _SYNC_BUILTINS
            and len(call.args) == 1
            and taint.expr(call.args[0])
        ):
            self._emit(
                ctx,
                call,
                findings,
                f"`{call.func.id}()` on a device value forces a blocking host "
                "sync; pull via _device_get first",
            )
            return
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _SYNC_METHODS
            and taint.expr(call.func.value)
        ):
            self._emit(
                ctx,
                call,
                findings,
                f"`.{call.func.attr}()` on a device value forces a blocking "
                "host sync; pull via _device_get first",
            )
            return
        # Interprocedural: a tainted argument handed to a local helper whose
        # summary says it syncs that parameter.  The sync happens one frame
        # down; the hot-path call site is where the fix belongs.
        if taint.summaries is not None:
            callee = taint.summaries.resolve_call(call, taint.scope)
            if callee is not None:
                synced = taint.summaries.sync_params(callee)
                if synced:
                    for pname, arg in callee.bind_args(call):
                        if pname in synced and taint.expr(arg):
                            self._emit(
                                ctx,
                                call,
                                findings,
                                f"device value passed to `{callee.name}()`, "
                                f"which forces a host sync on parameter "
                                f"`{pname}`; pull via _device_get first or "
                                "pass a host copy",
                            )
                            break

    def _check_truthiness(self, ctx, test, taint, findings) -> None:
        # `if device_array:` / `while not mask:` — __bool__ on a jax array is
        # a hidden sync (and a ConcretizationError under jit).
        candidates = [test]
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            candidates.append(test.operand)
        if isinstance(test, ast.BoolOp):
            candidates.extend(test.values)
        for cand in candidates:
            if isinstance(cand, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in cand.ops
            ):
                continue  # `x is None` never syncs, tainted or not
            if isinstance(cand, (ast.Name, ast.Attribute, ast.Subscript, ast.BinOp, ast.Compare, ast.Call)):
                if taint.expr(cand):
                    self._emit(
                        ctx,
                        test,
                        findings,
                        "truthiness of a device value in a branch condition "
                        "forces a hidden host sync; compare on a host copy",
                    )
                    return

    def _emit(self, ctx, node, findings, message: str) -> None:
        if findings is None:
            return  # taint-building pass
        key = (node.lineno, node.col_offset, message)
        if key not in findings:
            findings[key] = ctx.finding(self.name, node, message)
