"""graftlint v2 interprocedural layer: call graph + function summaries.

The r09 analyzers were strictly intraprocedural: taint, ownership and
dominance facts died at every call boundary, so `host-sync` could not see
that a helper forces a pull on its argument and no rule could see that
`_prefix_copy_in` leaks a block acquired two frames up.  This module is the
shared v2 substrate:

* a **function table** over one module's AST — every ``def`` (functions,
  methods, nested defs) keyed by dotted qualname, with a tail-name index
  for method-style call resolution;
* **call resolution** — ``helper(...)`` to a module-level function,
  ``self.m(...)``/``cls.m(...)`` to a method of the enclosing class,
  ``Class(...)`` to ``Class.__init__`` (constructor stores count as
  ownership transfer);
* **per-function summaries**, each computed intrinsically first and then
  propagated **one level** through direct callees (the ISSUE-16 contract:
  taint and ownership flow through helper calls, but not through arbitrary
  call chains — deeper facts must be re-established by the callee's own
  summary at its own call sites):

  ===================  ====================================================
  ``returns_device``   the return value is derived from ``jnp.*``/``jax.*``
                       /``lax.*`` expressions, module-level jitted calls,
                       or (one level) a local callee that returns one
  ``sync_params``      parameter names the body forces a device->host sync
                       on (``np.asarray``, ``float()``/``int()``/``bool()``,
                       ``.item()``/``.tolist()``, truthiness, device_get)
  ``stores_params``    parameter names the body stores into longer-lived
                       storage (``self.attr = p``, ``self.tbl[i] = p``,
                       ``self.lst.append(p)``) — ownership transfer sinks
  ``releases_params``  parameter names the body passes to a release call
                       (``decref``)
  ``returns_acquired`` the function returns the (possibly None-checked)
                       result of an acquire call (``alloc``/one-level
                       acquired-returning callee) — calling it IS acquiring
  ``calls_flush``      the body calls ``_flush_pipeline`` (directly or one
                       level down)
  ===================  ====================================================

Summaries are resolved lazily and memoised per :class:`ModuleSummaries`,
which is itself cached on the :class:`~.core.FileContext` (``ctx.summaries``)
so the host-sync, kv-refcount, flush-order and sharding-pin analyzers share
one pass worth of work per file.  Resolution is module-local by design:
cross-module imports are NOT followed (a summary for an imported helper
would need whole-program analysis; the per-module invariants the rules
encode don't).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu._private.lint.core import collect_jitted, dotted_name

#: dotted tails whose call allocates refcounted block handles
ACQUIRE_TAILS = ("alloc",)
#: dotted tails whose call adds a holder to already-allocated blocks
INCREF_TAILS = ("incref",)
#: dotted tails whose call drops a holder
RELEASE_TAILS = ("decref",)
#: method names that flush the async dispatch ring
FLUSH_TAILS = ("_flush_pipeline",)

_SYNC_BUILTINS = {"float", "int", "bool"}
_SYNC_METHODS = {"item", "tolist"}
_NP_SYNC_TAILS = {"asarray", "array", "ascontiguousarray"}
_DEVICE_ROOTS = {"jnp", "jax", "lax"}


def call_tail(call: ast.Call) -> str:
    """Final attribute/name component of a call target
    (``self.kv_pool.alloc`` -> "alloc", ``helper`` -> "helper")."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


@dataclasses.dataclass
class FunctionInfo:
    """One ``def`` in the module, with enough signature context to map
    call-site arguments back onto parameter names."""

    qualname: str
    name: str
    node: ast.AST                      # FunctionDef | AsyncFunctionDef
    params: List[str]                  # positional params, ``self`` dropped
    is_method: bool
    class_name: str = ""

    def bind_args(self, call: ast.Call) -> List[Tuple[str, ast.expr]]:
        """(param_name, argument_expr) pairs for a call site; positional
        args past the known params and ``*args`` splats are skipped."""
        bound: List[Tuple[str, ast.expr]] = []
        for idx, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if idx < len(self.params):
                bound.append((self.params[idx], arg))
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in self.params:
                bound.append((kw.arg, kw.value))
        return bound


class ModuleSummaries:
    """Function table + memoised one-level summaries for one parsed module."""

    def __init__(self, tree: ast.Module,
                 sync_exempt: frozenset = frozenset()):
        self.tree = tree
        self.sync_exempt = sync_exempt
        self.jitted = set(collect_jitted(tree))
        self.functions: Dict[str, FunctionInfo] = {}
        self.by_tail: Dict[str, List[FunctionInfo]] = {}
        self._classes: Dict[str, ast.ClassDef] = {}
        self._collect(tree, prefix="", class_name="")
        self._returns_device: Dict[str, bool] = {}
        self._sync_params: Dict[str, Set[str]] = {}
        self._stores_params: Dict[str, Set[str]] = {}
        self._releases_params: Dict[str, Set[str]] = {}
        self._returns_acquired: Dict[str, bool] = {}
        self._calls_flush: Dict[str, bool] = {}

    # -- table construction --------------------------------------------------

    def _collect(self, node: ast.AST, prefix: str, class_name: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._classes[child.name] = child
                qual = f"{prefix}{child.name}"
                self._collect(child, prefix=qual + ".",
                              class_name=child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                params = [a.arg for a in child.args.posonlyargs] + \
                         [a.arg for a in child.args.args]
                is_method = bool(class_name) and not any(
                    dotted_name(d) == "staticmethod"
                    for d in child.decorator_list)
                if is_method and params:
                    params = params[1:]        # drop self/cls
                params += [a.arg for a in child.args.kwonlyargs]
                info = FunctionInfo(qualname=qual, name=child.name,
                                    node=child, params=params,
                                    is_method=is_method,
                                    class_name=class_name)
                self.functions[qual] = info
                self.by_tail.setdefault(child.name, []).append(info)
                self._collect(child, prefix=qual + ".",
                              class_name=class_name)

    # -- call resolution -----------------------------------------------------

    def resolve_call(self, call: ast.Call,
                     scope: Optional[FunctionInfo] = None
                     ) -> Optional[FunctionInfo]:
        """Map a call site to a module-local FunctionInfo, or None.

        ``helper(...)``        module function (or unique tail)
        ``self.m(...)``        method ``m`` of the enclosing class (scope)
        ``Class(...)``         ``Class.__init__``
        ``obj.m(...)``         unique in-module method named ``m`` — tail
                               fallback, same heuristic jit-hygiene uses
        """
        fn = call.func
        if isinstance(fn, ast.Name):
            name = fn.id
            if name in self._classes:
                return self.functions.get(f"{name}.__init__")
            info = self.functions.get(name)
            if info is not None:
                return info
            cands = [i for i in self.by_tail.get(name, ())
                     if "." not in i.qualname]
            return cands[0] if len(cands) == 1 else None
        if isinstance(fn, ast.Attribute):
            tail = fn.attr
            recv = fn.value
            if isinstance(recv, ast.Name) and recv.id in ("self", "cls") \
                    and scope is not None and scope.class_name:
                info = self.functions.get(f"{scope.class_name}.{tail}")
                if info is not None:
                    return info
            if isinstance(recv, ast.Name) and recv.id in self._classes:
                return self.functions.get(f"{recv.id}.{tail}")
            cands = self.by_tail.get(tail, ())
            return cands[0] if len(cands) == 1 else None
        return None

    def info_for(self, node: ast.AST) -> Optional[FunctionInfo]:
        """FunctionInfo for a specific def node (identity match)."""
        name = getattr(node, "name", "")
        for info in self.by_tail.get(name, ()):
            if info.node is node:
                return info
        return None

    def scope_of(self, node: ast.AST,
                 parents: Dict[ast.AST, ast.AST]) -> Optional[FunctionInfo]:
        """FunctionInfo of the def enclosing ``node`` (via a parent map)."""
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for info in self.by_tail.get(cur.name, ()):
                    if info.node is cur:
                        return info
            cur = parents.get(cur)
        return None

    # -- summary: returns_device --------------------------------------------

    def returns_device(self, info: FunctionInfo) -> bool:
        """Does the function return a device-derived value?  One level:
        returns of calls to local callees use the callee's *intrinsic*
        fact, so taint crosses exactly one helper boundary."""
        if info.qualname not in self._returns_device:
            self._returns_device[info.qualname] = \
                self._compute_returns_device(info, follow=True)
        return self._returns_device[info.qualname]

    def _compute_returns_device(self, info: FunctionInfo,
                                follow: bool) -> bool:
        if info.name in self.sync_exempt:
            # Choke points (``_device_get``) exist to RETURN host copies.
            return False
        device_locals: Set[str] = set()
        changed = True
        while changed:            # _own_nodes is unordered: iterate to fixpoint
            changed = False
            for node in self._own_nodes(info):
                if isinstance(node, ast.Assign):
                    if self._expr_device(node.value, device_locals, info,
                                         follow):
                        for tgt in node.targets:
                            for n in ast.walk(tgt):
                                if isinstance(n, ast.Name) and \
                                        n.id not in device_locals:
                                    device_locals.add(n.id)
                                    changed = True
        for node in self._own_nodes(info):
            if isinstance(node, ast.Return) and node.value is not None:
                if self._expr_device(node.value, device_locals, info,
                                     follow):
                    return True
        return False

    def _expr_device(self, expr: ast.AST, device_locals: Set[str],
                     scope: FunctionInfo, follow: bool) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in device_locals:
                return True
            if isinstance(node, ast.Call):
                fn = dotted_name(node.func)
                root = fn.split(".", 1)[0] if fn else ""
                if root in _DEVICE_ROOTS or fn in self.jitted:
                    return True
                if follow:
                    callee = self.resolve_call(node, scope)
                    if callee is not None and callee is not scope and \
                            self._intrinsic_returns_device(callee):
                        return True
        return False

    def _intrinsic_returns_device(self, info: FunctionInfo) -> bool:
        key = "~" + info.qualname
        if key not in self._returns_device:
            self._returns_device[key] = False      # cycle guard
            self._returns_device[key] = \
                self._compute_returns_device(info, follow=False)
        return self._returns_device[key]

    # -- summary: sync_params ------------------------------------------------

    def sync_params(self, info: FunctionInfo) -> Set[str]:
        """Parameter names the body forces a host sync on (intrinsic
        only — the call-site rule provides the one level of propagation
        by reporting at the tainted caller)."""
        if info.qualname not in self._sync_params:
            self._sync_params[info.qualname] = self._compute_sync(info)
        return self._sync_params[info.qualname]

    def _compute_sync(self, info: FunctionInfo) -> Set[str]:
        if info.name in self.sync_exempt:
            return set()
        names = set(info.params)
        if not names:
            return set()
        synced: Set[str] = set()

        def param_rooted(expr: ast.AST) -> Optional[str]:
            cur = expr
            while isinstance(cur, (ast.Attribute, ast.Subscript)):
                if isinstance(cur, ast.Attribute) and cur.attr in (
                        "shape", "ndim", "dtype", "size", "nbytes",
                        "sharding", "device", "itemsize"):
                    return None
                cur = cur.value
            if isinstance(cur, ast.Name) and cur.id in names:
                return cur.id
            return None

        for node in self._own_nodes(info):
            if isinstance(node, ast.Call):
                fn = dotted_name(node.func)
                tail = call_tail(node)
                if fn in ("jax.device_get", "jax.block_until_ready") \
                        and node.args:
                    p = param_rooted(node.args[0])
                    if p:
                        synced.add(p)
                elif tail in _NP_SYNC_TAILS and \
                        fn.split(".", 1)[0] in ("np", "numpy") and node.args:
                    p = param_rooted(node.args[0])
                    if p:
                        synced.add(p)
                elif isinstance(node.func, ast.Name) and \
                        node.func.id in _SYNC_BUILTINS and \
                        len(node.args) == 1:
                    p = param_rooted(node.args[0])
                    if p:
                        synced.add(p)
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr in _SYNC_METHODS:
                    p = param_rooted(node.func.value)
                    if p:
                        synced.add(p)
            elif isinstance(node, (ast.If, ast.While)):
                test = node.test
                if isinstance(test, ast.Compare) and all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in test.ops):
                    continue                       # `p is None` never syncs
                if isinstance(test, (ast.Name, ast.Attribute,
                                     ast.Subscript)):
                    p = param_rooted(test)
                    if p:
                        synced.add(p)
        return synced

    # -- summary: stores / releases -----------------------------------------

    def stores_params(self, info: FunctionInfo) -> Set[str]:
        """Params stored into attribute/subscript targets rooted at
        ``self`` (or any non-local receiver) or appended/extended into
        one — the ownership-transfer sinks for kv-refcount."""
        if info.qualname not in self._stores_params:
            self._stores_params[info.qualname] = self._compute_stores(info)
        return self._stores_params[info.qualname]

    def _compute_stores(self, info: FunctionInfo) -> Set[str]:
        names = set(info.params)
        if not names:
            return set()
        stored: Set[str] = set()

        def mentions(expr: ast.AST) -> Set[str]:
            return {n.id for n in ast.walk(expr)
                    if isinstance(n, ast.Name) and n.id in names}

        locals_seen: Set[str] = set()
        for node in self._own_nodes(info):
            if isinstance(node, ast.Assign):
                hit = mentions(node.value)
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        root = tgt
                        while isinstance(root, (ast.Attribute,
                                                ast.Subscript)):
                            root = root.value
                        if not (isinstance(root, ast.Name)
                                and root.id in locals_seen):
                            stored |= hit
                    elif isinstance(tgt, ast.Name):
                        locals_seen.add(tgt.id)
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("append", "extend", "add",
                                       "setdefault", "update"):
                root = node.func.value
                while isinstance(root, (ast.Attribute, ast.Subscript)):
                    root = root.value
                if isinstance(root, ast.Name) and root.id in locals_seen:
                    continue
                for arg in node.args:
                    stored |= mentions(arg)
        return stored

    def releases_params(self, info: FunctionInfo) -> Set[str]:
        if info.qualname not in self._releases_params:
            out: Set[str] = set()
            names = set(info.params)
            for node in self._own_nodes(info):
                if isinstance(node, ast.Call) and \
                        call_tail(node) in RELEASE_TAILS:
                    for arg in node.args:
                        for n in ast.walk(arg):
                            if isinstance(n, ast.Name) and n.id in names:
                                out.add(n.id)
            self._releases_params[info.qualname] = out
        return self._releases_params[info.qualname]

    # -- summary: returns_acquired ------------------------------------------

    def returns_acquired(self, info: FunctionInfo) -> bool:
        """True when calling this function hands the caller freshly
        acquired block handles: the body returns the (possibly
        None-checked) result of an acquire call, or — one level — of a
        local callee that intrinsically returns one."""
        if info.qualname not in self._returns_acquired:
            self._returns_acquired[info.qualname] = False   # cycle guard
            self._returns_acquired[info.qualname] = \
                self._compute_returns_acquired(info)
        return self._returns_acquired[info.qualname]

    def _compute_returns_acquired(self, info: FunctionInfo) -> bool:
        acquired_locals: Set[str] = set()

        def is_acquire(call: ast.Call) -> bool:
            if call_tail(call) in ACQUIRE_TAILS:
                return True
            callee = self.resolve_call(call, info)
            return (callee is not None and callee is not info
                    and self.returns_acquired(callee))

        for node in self._own_nodes(info):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    is_acquire(node.value):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        acquired_locals.add(tgt.id)
        for node in self._own_nodes(info):
            if isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                if isinstance(v, ast.Call) and is_acquire(v):
                    return True
                for n in ast.walk(v):
                    if isinstance(n, ast.Name) and n.id in acquired_locals:
                        return True
        return False

    # -- summary: calls_flush ------------------------------------------------

    def calls_flush(self, info: FunctionInfo) -> bool:
        """Body calls ``_flush_pipeline`` — directly or one level down."""
        if info.qualname not in self._calls_flush:
            self._calls_flush[info.qualname] = False        # cycle guard
            hit = False
            for node in self._own_nodes(info):
                if isinstance(node, ast.Call):
                    if call_tail(node) in FLUSH_TAILS:
                        hit = True
                        break
                    callee = self.resolve_call(node, info)
                    if callee is not None and callee is not info and \
                            self._intrinsic_calls_flush(callee):
                        hit = True
                        break
            self._calls_flush[info.qualname] = hit
        return self._calls_flush[info.qualname]

    def _intrinsic_calls_flush(self, info: FunctionInfo) -> bool:
        key = "~" + info.qualname
        if key not in self._calls_flush:
            self._calls_flush[key] = any(
                isinstance(n, ast.Call) and call_tail(n) in FLUSH_TAILS
                for n in self._own_nodes(info))
        return self._calls_flush[key]

    # -- helpers -------------------------------------------------------------

    def _own_nodes(self, info: FunctionInfo):
        """Walk a function's body EXCLUDING nested def/class scopes."""
        stack = list(ast.iter_child_nodes(info.node))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))
