"""kv-refcount: BlockPool acquire/release balance on all exit paths.

The paged KV planner hands out block ids through a host-side refcount
ledger (``BlockPool.alloc`` -> refcount 1, ``incref`` -> +1 per sharer,
``decref`` -> -1, freed at zero).  The runtime identity tests catch a
drifted ledger only when a seeded workload happens to hit the leaky path;
this analyzer checks the discipline statically: **every acquire must reach
a matching release or ownership transfer on every exit — including
exception edges — and nothing may be released twice.**

Ownership model (per function, module-local):

* **acquire** — binding the result of an ``*.alloc(...)`` call or of a
  local callee whose summary says ``returns_acquired`` (``_pool_alloc``);
  ``incref(name)`` also acquires: it creates one more obligation on the
  blocks ``name`` denotes.
* **release** — ``decref(name)``.  A second ``decref`` of the same
  obligation is a double-free finding.
* **transfer** — ownership leaves the frame: the name is stored into an
  attribute/subscript/container (``self._row_blocks[row] = chain``,
  ``self._bt.append(ids)``), returned or yielded, passed to a local callee
  whose summary stores or releases that parameter (``_bind_row``,
  ``_Node(...)``), or passed to a call the module summaries cannot resolve
  (cross-module escape — module-local precision by design).
* **move** — ``chain = shared + new_ids`` shifts the obligations of the
  mentioned owned names onto the new binding.
* **None narrowing** — inside ``if x is None:`` (and the body of
  ``while x is None:`` retry loops) the acquire failed, so ``x`` owns
  nothing on that path.

Exits checked: ``return`` / ``yield`` (owned names not in the returned
expression leak), ``raise`` outside a same-function handler (the
leak-on-raise class the runtime tests cannot see), ``continue`` and
for-loop iteration end for names acquired inside that loop, and function
fall-through.  Branch merges are may-analysis: released on *some* paths
but owned on others reports "not released on all paths".

Fires only on the files that own pool handles (``engine.py``,
``prefix_cache.py``, ``block_pool.py``) or under ``force_hot``.
"""

from __future__ import annotations

import ast
import copy
from typing import Dict, List, Optional, Set

from ray_tpu._private.lint.core import FileContext, Finding, Rule, register
from ray_tpu._private.lint.dataflow import (
    ACQUIRE_TAILS,
    INCREF_TAILS,
    RELEASE_TAILS,
    call_tail,
)

_OWNED = "owned"
_MAYBE = "maybe"          # released/transferred on some paths only
_RELEASED = "released"
_TRANSFERRED = "transferred"


class _Obligation:
    __slots__ = ("state", "node", "loop_depth")

    def __init__(self, state: str, node: ast.AST, loop_depth: int):
        self.state = state
        self.node = node
        self.loop_depth = loop_depth


class _FnChecker:
    """Single-function ownership walk (source order, branch-merging)."""

    def __init__(self, rule: "KvRefcountRule", ctx: FileContext,
                 fn: ast.AST):
        self.rule = rule
        self.ctx = ctx
        self.fn = fn
        self.summaries = ctx.summaries
        self.scope = self.summaries.info_for(fn)
        self.findings: Dict[tuple, Finding] = {}
        self.state: Dict[str, _Obligation] = {}
        self.loop_depth = 0
        self.try_depth = 0          # inside a try body that has handlers

    def run(self) -> List[Finding]:
        terminated = self._walk_body(self.fn.body)
        if not terminated:
            self._check_exit("falling off the end of the function", self.fn)
        return list(self.findings.values())

    # -- findings ------------------------------------------------------------

    def _emit(self, node: ast.AST, message: str) -> None:
        key = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0),
               message)
        if key not in self.findings:
            self.findings[key] = self.ctx.finding(
                self.rule.name, node, message)

    def _check_exit(self, how: str, at: ast.AST,
                    keep: Set[str] = frozenset(),
                    min_depth: Optional[int] = None) -> None:
        """Report owned obligations that do not survive this exit.

        One finding per acquire site: an acquire that leaks on several
        exits (loop iteration AND fall-through) is one bug, keyed so the
        first-seen exit describes it."""
        line = getattr(at, "lineno", 0)
        for name, ob in self.state.items():
            if name in keep or ob.state not in (_OWNED, _MAYBE):
                continue
            if min_depth is not None and ob.loop_depth < min_depth:
                continue
            key = ("leak", getattr(ob.node, "lineno", 0),
                   getattr(ob.node, "col_offset", 0), name)
            if key in self.findings:
                continue
            qualifier = "" if ob.state == _OWNED else " on some paths"
            self.findings[key] = self.ctx.finding(
                self.rule.name,
                ob.node,
                f"block handles acquired into `{name}` are not released or "
                f"transferred{qualifier} when {how} (line {line}) — "
                "refcount leak",
            )

    # -- events --------------------------------------------------------------

    def _is_acquire_call(self, call: ast.Call) -> bool:
        if call_tail(call) in ACQUIRE_TAILS:
            return True
        callee = self.summaries.resolve_call(call, self.scope)
        return callee is not None and self.summaries.returns_acquired(callee)

    def _acquire(self, name: str, node: ast.AST) -> None:
        prev = self.state.get(name)
        if prev is not None and prev.state in (_OWNED, _MAYBE):
            self._emit(
                prev.node,
                f"block handles acquired into `{name}` are overwritten by a "
                f"new acquire at line {getattr(node, 'lineno', 0)} without a "
                "release — refcount leak",
            )
        self.state[name] = _Obligation(_OWNED, node, self.loop_depth)

    def _mentioned_tracked(self, expr: ast.AST) -> List[str]:
        out = []
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in self.state and \
                    self.state[n.id].state in (_OWNED, _MAYBE):
                out.append(n.id)
        return out

    def _settle(self, names, state: str) -> None:
        for name in names:
            ob = self.state.get(name)
            if ob is not None:
                ob.state = state

    # -- calls ---------------------------------------------------------------

    def _handle_call(self, call: ast.Call, is_stmt: bool) -> None:
        tail = call_tail(call)
        if tail in RELEASE_TAILS:
            for arg in call.args:
                for name in {n.id for n in ast.walk(arg)
                             if isinstance(n, ast.Name)
                             and n.id in self.state}:
                    ob = self.state[name]
                    if ob.state == _RELEASED:
                        self._emit(
                            call,
                            f"`{name}` is decref'd again after its "
                            "obligation was already released — double free",
                        )
                    ob.state = _RELEASED
            return
        if tail in INCREF_TAILS:
            if len(call.args) == 1 and isinstance(call.args[0], ast.Name):
                self._acquire(call.args[0].id, call)
            return
        if is_stmt and self._is_acquire_call(call):
            self._emit(
                call,
                "acquire result discarded: the allocated block handles can "
                "never be released — refcount leak",
            )
            return
        tracked = self._mentioned_tracked(call)
        if not tracked:
            return
        callee = self.summaries.resolve_call(call, self.scope)
        if callee is None:
            # Cross-module / unresolvable callee: assume the callee takes
            # ownership (escape).  Module-local precision, documented.
            self._settle(tracked, _TRANSFERRED)
            return
        sinks = self.summaries.stores_params(callee) | \
            self.summaries.releases_params(callee)
        bound_params = {}
        for pname, arg in callee.bind_args(call):
            for name in self._mentioned_tracked(arg):
                bound_params.setdefault(name, set()).add(pname)
        for name in tracked:
            params = bound_params.get(name)
            if params is None:
                # starred/overflow argument we could not bind: escape.
                self._settle([name], _TRANSFERRED)
            elif params & sinks:
                self._settle([name], _TRANSFERRED)
            # else: the callee provably neither stores nor releases it —
            # the obligation stays with this frame.

    def _scan_calls(self, expr: ast.AST, top_stmt: bool = False) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._handle_call(node, is_stmt=top_stmt and node is expr)

    # -- statements ----------------------------------------------------------

    def _walk_body(self, body) -> bool:
        """Walk statements in order; True when every path terminated."""
        for stmt in body:
            if self._walk_stmt(stmt):
                return True
        return False

    def _walk_stmt(self, stmt) -> bool:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return False            # nested scopes checked independently
        if isinstance(stmt, ast.Assign):
            self._handle_assign(stmt)
            return False
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._handle_assign(ast.Assign(
                    targets=[stmt.target], value=stmt.value,
                    lineno=stmt.lineno, col_offset=stmt.col_offset))
            return False
        if isinstance(stmt, ast.AugAssign):
            self._scan_calls(stmt.value)
            # `self.x += ids` style accumulation is a store.
            if isinstance(stmt.target, (ast.Attribute, ast.Subscript)):
                self._settle(self._mentioned_tracked(stmt.value),
                             _TRANSFERRED)
            return False
        if isinstance(stmt, ast.Expr):
            if isinstance(stmt.value, ast.Call):
                self._scan_calls(stmt.value, top_stmt=True)
            elif isinstance(stmt.value, (ast.Yield, ast.YieldFrom)):
                inner = stmt.value.value
                if inner is not None:
                    self._scan_calls(inner)
                    self._settle(self._mentioned_tracked(inner),
                                 _TRANSFERRED)
            else:
                self._scan_calls(stmt.value)
            return False
        if isinstance(stmt, ast.Return):
            keep: Set[str] = set()
            if stmt.value is not None:
                self._scan_calls(stmt.value)
                keep = set(self._mentioned_tracked(stmt.value))
                self._settle(keep, _TRANSFERRED)
            self._check_exit("returning", stmt, keep=keep)
            return True
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._scan_calls(stmt.exc)
            if self.try_depth == 0:
                self._check_exit("raising", stmt)
            return True
        if isinstance(stmt, ast.If):
            return self._handle_if(stmt)
        if isinstance(stmt, ast.While):
            return self._handle_while(stmt)
        if isinstance(stmt, ast.For):
            return self._handle_for(stmt)
        if isinstance(stmt, ast.Try):
            return self._handle_try(stmt)
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                self._scan_calls(item.context_expr)
            return self._walk_body(stmt.body)
        if isinstance(stmt, ast.Continue):
            self._check_exit("continuing the loop", stmt,
                             min_depth=self.loop_depth)
            return True
        if isinstance(stmt, ast.Break):
            return True             # ownership survives to after the loop
        if isinstance(stmt, (ast.Assert, ast.Delete, ast.Global,
                             ast.Nonlocal, ast.Pass, ast.Import,
                             ast.ImportFrom)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._scan_calls(child)
            return False
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_calls(child)
        return False

    def _handle_assign(self, stmt: ast.Assign) -> None:
        value = stmt.value
        self._scan_calls(value)
        target = stmt.targets[0] if len(stmt.targets) == 1 else None
        acquired = isinstance(value, ast.Call) and \
            self._is_acquire_call(value)
        moved = self._mentioned_tracked(value)
        if isinstance(target, ast.Name):
            if acquired:
                self._acquire(target.id, stmt)
                return
            if moved:
                # move: `chain = shared + new_ids` shifts the obligations
                depth = min(self.state[n].loop_depth for n in moved)
                self._settle(moved, _TRANSFERRED)
                self.state[target.id] = _Obligation(_OWNED, stmt, depth)
                return
            prev = self.state.get(target.id)
            if prev is not None and prev.state in (_OWNED, _MAYBE):
                self._emit(
                    prev.node,
                    f"block handles acquired into `{target.id}` are "
                    f"overwritten at line {stmt.lineno} without a release "
                    "— refcount leak",
                )
            self.state.pop(target.id, None)
            return
        if target is not None and isinstance(
                target, (ast.Attribute, ast.Subscript)):
            # store into longer-lived storage: ownership transferred
            self._settle(moved, _TRANSFERRED)
            return
        if acquired:
            # tuple-unpack of an acquire: untracked, warn nothing (rare)
            return
        self._settle(moved, _TRANSFERRED)   # conservative escape

    def _handle_if(self, stmt: ast.If) -> bool:
        self._scan_calls(stmt.test)
        narrow_none, narrow_some = self._none_narrowing(stmt.test)
        saved = self._snapshot()
        # then-branch
        for name in narrow_none:
            self.state.pop(name, None)      # x is None: nothing owned here
        t_term = self._walk_body(stmt.body)
        t_state = self._snapshot()
        # else-branch
        self._restore(saved)
        for name in narrow_some:
            self.state.pop(name, None)      # x is not None -> else: None
        e_term = self._walk_body(stmt.orelse)
        e_state = self._snapshot()
        if t_term and e_term:
            return True
        if t_term:
            self._restore(e_state)
        elif e_term:
            self._restore(t_state)
        else:
            self._restore(self._merge(t_state, e_state))
        return False

    def _handle_while(self, stmt: ast.While) -> bool:
        self._scan_calls(stmt.test)
        narrow_none, _ = self._none_narrowing(stmt.test)
        entry = self._snapshot()
        for name in narrow_none:
            self.state.pop(name, None)      # retry loop: alloc failed
        self.loop_depth += 1
        self._walk_body(stmt.body)
        self.loop_depth -= 1
        # No end-of-iteration check for while loops: the dominant shape is
        # the alloc-retry loop whose condition re-narrows the handle.
        merged = self._merge(entry, self._snapshot())
        self._restore(merged)
        if stmt.orelse:
            return self._walk_body(stmt.orelse)
        return False

    def _handle_for(self, stmt: ast.For) -> bool:
        self._scan_calls(stmt.iter)
        entry = self._snapshot()
        self.loop_depth += 1
        terminated = self._walk_body(stmt.body)
        if not terminated:
            # End of an iteration: anything acquired inside this loop and
            # still owned is re-leaked every pass.
            self._check_exit("finishing a loop iteration", stmt,
                             min_depth=self.loop_depth)
        self.loop_depth -= 1
        merged = self._merge(entry, self._snapshot())
        self._restore(merged)
        if stmt.orelse:
            return self._walk_body(stmt.orelse)
        return False

    def _handle_try(self, stmt: ast.Try) -> bool:
        pre = self._snapshot()
        if stmt.handlers:
            self.try_depth += 1
        body_term = self._walk_body(stmt.body)
        if stmt.handlers:
            self.try_depth -= 1
        body_state = self._snapshot()
        states = [] if body_term else [body_state]
        for handler in stmt.handlers:
            # The body may have failed anywhere: the handler sees the merge
            # of entry and post-body obligations.
            self._restore(self._merge(pre, body_state))
            if not self._walk_body(handler.body):
                states.append(self._snapshot())
        if stmt.orelse and not body_term:
            self._restore(body_state)
            if not self._walk_body(stmt.orelse):
                states[0] = self._snapshot()
        if not states:
            return True
        merged = states[0]
        for other in states[1:]:
            merged = self._merge(merged, other)
        self._restore(merged)
        if stmt.finalbody:
            return self._walk_body(stmt.finalbody)
        return False

    # -- state plumbing ------------------------------------------------------

    def _snapshot(self) -> Dict[str, _Obligation]:
        # Per-entry shallow copies: obligation STATE forks per branch, but
        # the acquire AST node must stay the original object (findings
        # resolve their symbol through the file's parent map).
        return {name: _Obligation(ob.state, ob.node, ob.loop_depth)
                for name, ob in self.state.items()}

    def _restore(self, state: Dict[str, _Obligation]) -> None:
        self.state = state

    def _merge(self, a: Dict[str, _Obligation],
               b: Dict[str, _Obligation]) -> Dict[str, _Obligation]:
        out: Dict[str, _Obligation] = {}
        for name in set(a) | set(b):
            oa, ob = a.get(name), b.get(name)
            if oa is None or ob is None:
                live = oa or ob
                if live.state in (_OWNED, _MAYBE):
                    live = copy.copy(live)
                    live.state = _MAYBE    # owned on one path, absent on the other
                out[name] = live
                continue
            merged = copy.copy(oa)
            states = {oa.state, ob.state}
            if states == {_OWNED}:
                merged.state = _OWNED
            elif _OWNED in states or _MAYBE in states:
                merged.state = (_MAYBE if states & {_RELEASED, _TRANSFERRED,
                                                    _MAYBE}
                                else _OWNED)
            elif states == {_RELEASED}:
                merged.state = _RELEASED
            else:
                merged.state = _TRANSFERRED
            out[name] = merged
        return out

    @staticmethod
    def _none_narrowing(test: ast.AST):
        """(names_none_in_then, names_none_in_else) for `x is None` tests."""
        none_then: Set[str] = set()
        none_else: Set[str] = set()
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.left, ast.Name) and \
                len(test.comparators) == 1 and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            if isinstance(test.ops[0], ast.Is):
                none_then.add(test.left.id)
            elif isinstance(test.ops[0], ast.IsNot):
                none_else.add(test.left.id)
        return none_then, none_else


@register
class KvRefcountRule(Rule):
    name = "kv-refcount"
    description = (
        "BlockPool acquire/incref must reach a matching decref or ownership "
        "transfer on every exit path (including raises); no double-frees"
    )

    def check(self, ctx: FileContext) -> List[Finding]:
        if not ctx.config.is_kv_path(ctx.path):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_FnChecker(self, ctx, node).run())
        return findings
