"""Env-gated cProfile hook for control-plane processes.

RAY_TPU_PROFILE=<prefix> makes gcs_server / raylet mains dump
<prefix>.<tag>.<pid>.prof at exit (SIGTERM-safe) — the way to see inside
spawned control processes in environments without py-spy/perf. Workers
use RAY_TPU_WORKER_PROFILE (worker_main.py).
"""

from __future__ import annotations

import os


def maybe_enable_profiler(tag: str):
    """Start a cProfile for this process when RAY_TPU_PROFILE is set;
    returns the profiler (or None). Dumps stats at exit, converting
    SIGTERM into a clean exit so atexit runs."""
    prefix = os.environ.get("RAY_TPU_PROFILE")
    if not prefix:
        return None
    import atexit
    import cProfile
    import signal
    import sys

    signal.signal(signal.SIGTERM, lambda *_: sys.exit(0))
    profiler = cProfile.Profile()
    profiler.enable()
    atexit.register(lambda: profiler.dump_stats(
        f"{prefix}.{tag}.{os.getpid()}.prof"))
    return profiler
