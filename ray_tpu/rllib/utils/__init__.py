"""RLlib utilities."""
