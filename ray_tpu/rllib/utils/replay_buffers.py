"""Replay buffers for off-policy algorithms.

Reference: rllib/utils/replay_buffers/ (ReplayBuffer,
PrioritizedEpisodeReplayBuffer). Transition-level ring buffer in numpy;
uniform and proportional-priority sampling.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ray_tpu.rllib.utils.sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform ring buffer over transitions."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._size = 0
        self._next = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: SampleBatch) -> None:
        n = len(batch)
        if n == 0:
            return
        if not self._cols:
            for k, v in batch.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = np.asarray(v)
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self.capacity, self._size + n)

    def sample(self, batch_size: int) -> SampleBatch:
        idx = self._rng.integers(0, self._size, batch_size)
        return SampleBatch({k: v[idx] for k, v in self._cols.items()})


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (reference: PER variants)."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._priorities = np.zeros(capacity, np.float32)
        self._max_priority = 1.0

    def add(self, batch: SampleBatch) -> None:
        n = len(batch)
        idx = (self._next + np.arange(n)) % self.capacity
        super().add(batch)
        self._priorities[idx] = self._max_priority

    def sample(self, batch_size: int) -> SampleBatch:
        probs = self._priorities[:self._size] ** self.alpha
        probs = probs / probs.sum()
        idx = self._rng.choice(self._size, batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights = weights / weights.max()
        out = SampleBatch({k: v[idx] for k, v in self._cols.items()})
        out["batch_indexes"] = idx
        out["weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> None:
        prios = np.abs(td_errors) + 1e-6
        self._priorities[idx] = prios
        self._max_priority = max(self._max_priority, float(prios.max()))
