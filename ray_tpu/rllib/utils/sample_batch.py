"""SampleBatch — columnar container for trajectory data.

Reference: python/ray/rllib/policy/sample_batch.py (SampleBatch). Columns
are numpy arrays with a shared leading (time/batch) dimension; the learner
converts to jax arrays at update time.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

# Canonical column names (reference: SampleBatch.OBS etc.)
OBS = "obs"
NEXT_OBS = "next_obs"
ACTIONS = "actions"
REWARDS = "rewards"
TERMINATEDS = "terminateds"
TRUNCATEDS = "truncateds"
ACTION_LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
EPS_ID = "eps_id"


class SampleBatch(dict):
    """dict of column -> np.ndarray with equal leading dimension."""

    def __len__(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    @property
    def count(self) -> int:
        return len(self)

    @staticmethod
    def concat_samples(batches: List["SampleBatch"]) -> "SampleBatch":
        if not batches:
            return SampleBatch()
        keys = batches[0].keys()
        return SampleBatch({
            k: np.concatenate([np.asarray(b[k]) for b in batches])
            for k in keys})

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(len(self))
        return SampleBatch({k: np.asarray(v)[perm] for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: np.asarray(v)[start:end]
                            for k, v in self.items()})

    def minibatches(self, size: int,
                    rng: np.random.Generator) -> Iterator["SampleBatch"]:
        """Shuffled minibatches; drops the ragged tail if smaller than
        size//2 (keeps jit shapes near-constant)."""
        shuffled = self.shuffle(rng)
        n = len(shuffled)
        for start in range(0, n, size):
            end = min(start + size, n)
            if end - start >= max(1, size // 2):
                yield shuffled.slice(start, end)

    def as_dict(self) -> Dict[str, np.ndarray]:
        return dict(self)
