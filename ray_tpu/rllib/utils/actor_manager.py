"""FaultTolerantActorManager — RPC fan-out with failure tolerance.

Reference: rllib/utils/actor_manager.py:196. Wraps a set of same-class
actors; foreach() fans a call out, collects results, marks actors that
raise as unhealthy, and can recreate them from a factory (restored actors
get the latest weights pushed by the caller).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Tuple

import ray_tpu

logger = logging.getLogger(__name__)


class RemoteCallResults:
    def __init__(self):
        self.ok: List[Tuple[int, Any]] = []
        self.errors: List[Tuple[int, Exception]] = []

    def values(self) -> List[Any]:
        return [v for _, v in sorted(self.ok)]


class FaultTolerantActorManager:
    def __init__(self, actors: List[Any],
                 actor_factory: Optional[Callable[[int], Any]] = None,
                 max_remote_requests_in_flight: int = 2):
        self._actors: Dict[int, Any] = dict(enumerate(actors))
        self._healthy: Dict[int, bool] = {i: True for i in self._actors}
        self._factory = actor_factory

    @property
    def num_actors(self) -> int:
        return len(self._actors)

    def num_healthy_actors(self) -> int:
        return sum(self._healthy.values())

    def healthy_actor_ids(self) -> List[int]:
        return [i for i, h in self._healthy.items() if h]

    def actor(self, actor_id: int) -> Any:
        return self._actors[actor_id]

    def foreach(self, fn: Callable[[Any], Any],
                *, healthy_only: bool = True,
                timeout_s: Optional[float] = None) -> RemoteCallResults:
        """fn maps an actor handle to an ObjectRef (e.g. lambda a:
        a.sample.remote(50)); results gathered with per-actor error
        isolation."""
        ids = self.healthy_actor_ids() if healthy_only \
            else list(self._actors)
        refs = {}
        results = RemoteCallResults()
        for i in ids:
            try:
                refs[i] = fn(self._actors[i])
            except Exception as e:  # submission itself failed
                self._mark_unhealthy(i, e)
                results.errors.append((i, e))
        for i, ref in refs.items():
            try:
                results.ok.append((i, ray_tpu.get(ref, timeout=timeout_s)))
            except Exception as e:
                self._mark_unhealthy(i, e)
                results.errors.append((i, e))
        return results

    def foreach_sharded(self, fn: Callable[[Any, Any], Any],
                        shards: Dict[int, Any], *,
                        timeout_s: Optional[float] = None
                        ) -> RemoteCallResults:
        """Per-actor-args variant of foreach: fn(actor, shard) -> ref,
        called once per (actor_id, shard) pair; same error isolation
        and unhealthy-marking semantics."""
        refs = {}
        results = RemoteCallResults()
        for i, shard in shards.items():
            if not self._healthy.get(i, False):
                continue
            try:
                refs[i] = fn(self._actors[i], shard)
            except Exception as e:
                self._mark_unhealthy(i, e)
                results.errors.append((i, e))
        for i, ref in refs.items():
            try:
                results.ok.append((i, ray_tpu.get(ref, timeout=timeout_s)))
            except Exception as e:
                self._mark_unhealthy(i, e)
                results.errors.append((i, e))
        return results

    def _mark_unhealthy(self, actor_id: int, error: Exception) -> None:
        logger.warning("actor %d failed: %s", actor_id, error)
        self._healthy[actor_id] = False

    def shutdown(self) -> None:
        """Kill every managed actor (best-effort) and drop the set."""
        for i in list(self._actors):
            try:
                ray_tpu.kill(self._actors[i])
            except Exception:
                pass
        self._actors.clear()
        self._healthy.clear()

    def probe_unhealthy(self) -> List[int]:
        """Ping unhealthy actors; recreate dead ones via the factory.
        Returns ids restored this call (caller re-syncs their state)."""
        restored = []
        for i, healthy in list(self._healthy.items()):
            if healthy:
                continue
            try:
                ray_tpu.get(self._actors[i].ping.remote(), timeout=5.0)
                self._healthy[i] = True
                restored.append(i)
            except Exception:
                if self._factory is not None:
                    try:
                        self._actors[i] = self._factory(i)
                        ray_tpu.get(self._actors[i].ping.remote(),
                                    timeout=10.0)
                        self._healthy[i] = True
                        restored.append(i)
                    except Exception as e:
                        logger.warning("restore of actor %d failed: %s",
                                       i, e)
        return restored
