"""Advantage estimation (GAE) over concatenated rollout batches.

Reference: rllib/evaluation/postprocessing.py (compute_advantages) /
connectors GeneralAdvantageEstimation. Computed host-side in numpy —
rollouts arrive as numpy and the scan is O(T) with trivial FLOPs, so
there is nothing for the MXU here.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.sample_batch import SampleBatch


def compute_gae(batch: SampleBatch, gamma: float, lambda_: float,
                bootstrap_value: float = 0.0) -> SampleBatch:
    """Adds ADVANTAGES and VALUE_TARGETS columns.

    Episode boundaries come from EPS_ID + TERMINATEDS/TRUNCATEDS; a rollout
    cut mid-episode bootstraps from `bootstrap_value` (the runner's value
    estimate of its current obs). Truncated (but not terminated) episodes
    bootstrap from the value prediction of their final next_obs — absent
    per-step next-values, we approximate with the last vf_pred, which is
    the standard one-step-stale bootstrap.
    """
    rewards = np.asarray(batch[sb.REWARDS], np.float32)
    values = np.asarray(batch[sb.VF_PREDS], np.float32)
    terminateds = np.asarray(batch[sb.TERMINATEDS], bool)
    truncateds = np.asarray(batch[sb.TRUNCATEDS], bool)
    eps_ids = np.asarray(batch[sb.EPS_ID])
    n = len(rewards)
    advantages = np.zeros(n, np.float32)
    last_gae = 0.0
    next_value = bootstrap_value
    for t in range(n - 1, -1, -1):
        boundary = (t == n - 1) or (eps_ids[t + 1] != eps_ids[t])
        if boundary:
            last_gae = 0.0
            if terminateds[t]:
                next_value = 0.0
            elif t == n - 1:
                # Chronologically-last step: caller's bootstrap is exact.
                next_value = bootstrap_value
            else:
                # Episode truncated or cut mid-batch: one-step-stale
                # bootstrap from its own last value estimate.
                next_value = values[t]
        delta = rewards[t] + gamma * next_value - values[t]
        last_gae = delta + gamma * lambda_ * last_gae
        advantages[t] = last_gae
        next_value = values[t]
    out = SampleBatch(batch)
    out[sb.ADVANTAGES] = advantages
    out[sb.VALUE_TARGETS] = advantages + values
    return out


def standardize(x: np.ndarray) -> np.ndarray:
    return (x - x.mean()) / max(1e-6, x.std())
