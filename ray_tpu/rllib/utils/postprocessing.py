"""Advantage estimation (GAE) over concatenated rollout batches.

Reference: rllib/evaluation/postprocessing.py (compute_advantages) /
connectors GeneralAdvantageEstimation. Computed host-side in numpy —
rollouts arrive as numpy and the scan is O(T) with trivial FLOPs, so
there is nothing for the MXU here.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.sample_batch import SampleBatch


def compute_gae(batch: SampleBatch, gamma: float, lambda_: float,
                bootstrap_value=0.0) -> SampleBatch:
    """Adds ADVANTAGES and VALUE_TARGETS columns.

    Episode boundaries come from EPS_ID + TERMINATEDS/TRUNCATEDS.
    ``bootstrap_value`` is either a scalar (exact bootstrap for the
    chronologically-last step only — single-env runners) or a dict
    {eps_id: value} of exact bootstraps for each env's final (possibly
    cut) episode — vector-env runners, whose batches are env-major.
    Boundaries without an exact bootstrap fall back to the standard
    one-step-stale bootstrap from the row's own value estimate.
    """
    rewards = np.asarray(batch[sb.REWARDS], np.float32)
    values = np.asarray(batch[sb.VF_PREDS], np.float32)
    terminateds = np.asarray(batch[sb.TERMINATEDS], bool)
    eps_ids = np.asarray(batch[sb.EPS_ID])
    boots = bootstrap_value if isinstance(bootstrap_value, dict) else None
    scalar_boot = 0.0 if boots is not None else float(bootstrap_value)
    n = len(rewards)
    advantages = np.zeros(n, np.float32)
    last_gae = 0.0
    next_value = scalar_boot
    for t in range(n - 1, -1, -1):
        boundary = (t == n - 1) or (eps_ids[t + 1] != eps_ids[t])
        if boundary:
            last_gae = 0.0
            if terminateds[t]:
                next_value = 0.0
            elif boots is not None and int(eps_ids[t]) in boots:
                # Exact per-env bootstrap (vector runners).
                next_value = boots[int(eps_ids[t])]
            elif boots is None and t == n - 1:
                # Chronologically-last step: caller's bootstrap is exact.
                next_value = scalar_boot
            else:
                # Episode truncated or cut mid-batch: one-step-stale
                # bootstrap from its own last value estimate.
                next_value = values[t]
        delta = rewards[t] + gamma * next_value - values[t]
        last_gae = delta + gamma * lambda_ * last_gae
        advantages[t] = last_gae
        next_value = values[t]
    out = SampleBatch(batch)
    out[sb.ADVANTAGES] = advantages
    out[sb.VALUE_TARGETS] = advantages + values
    return out


def standardize(x: np.ndarray) -> np.ndarray:
    return (x - x.mean()) / max(1e-6, x.std())
