"""SingleAgentEnvRunner — samples episodes with the current policy.

Reference: rllib/env/single_agent_env_runner.py:60 — env runners step
VECTOR envs: N sub-envs per runner advance per policy forward (one
batched jit call instead of N), and the built-in CartPole runs fully
numpy-vectorized (env/vector.py). Runs as a CPU actor: holds the env +
an RLModule evaluated eagerly from host weights, returns SampleBatches
through the object store.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env.vector import make_vector_env
from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class SingleAgentEnvRunner:
    """One rollout worker stepping a vector of envs. Methods are called
    via actor RPCs."""

    def __init__(self, config: dict, worker_index: int = 0):
        import jax

        self.config = config
        self.worker_index = worker_index
        self.num_envs = max(1, int(config.get("num_envs_per_runner", 1)))
        seed = config.get("seed", 0) * 1000 + worker_index
        self.env = make_vector_env(config["env"],
                                   config.get("env_config"),
                                   self.num_envs, seed=seed)
        spec = config["module_spec"]
        self.module = spec.build()
        self._rng = jax.random.PRNGKey(seed)
        self._np_rng = np.random.default_rng(seed)
        self.params = None
        self.env.reset(seed=seed)
        self._episode_return = np.zeros(self.num_envs, np.float64)
        # Distinct eps-id ranges per (worker, sub-env).
        self._eps_id = np.array(
            [(worker_index * self.num_envs + i) * 1_000_000
             for i in range(self.num_envs)], np.int64)
        self._recent_returns: collections.deque = collections.deque(
            maxlen=100)
        self._explore_fn = None
        self._total_steps = 0
        # ConnectorV2 pipelines (reference: rllib/connectors/): user
        # env_to_module/module_to_env factories from the config, plus
        # the default EpsilonGreedy module_to_env connector — the runner
        # itself contains no hard-wired preprocessing.
        from ray_tpu.rllib.connectors.connector import (EpsilonGreedy,
                                                        build_pipeline)

        self._env_to_module = build_pipeline(
            config.get("env_to_module_connector"))
        self._module_to_env = build_pipeline(
            config.get("module_to_env_connector"))
        self._module_to_env.append(EpsilonGreedy())
        self._prev_dones = np.ones(self.num_envs, bool)  # fresh episodes

    def _obs_in(self, obs: np.ndarray) -> np.ndarray:
        """env_to_module transform for the obs the policy will act on
        (advances connector state; resets per-env state after dones)."""
        if not len(self._env_to_module):
            return obs
        return self._env_to_module({"obs": obs},
                                   dones=self._prev_dones)["obs"]

    def _obs_peek(self, obs: np.ndarray, dones: np.ndarray) -> np.ndarray:
        """env_to_module transform WITHOUT advancing state (recording
        next_obs / value bootstraps)."""
        if not len(self._env_to_module):
            return obs
        return self._env_to_module({"obs": obs}, dones=dones,
                                   commit=False)["obs"]

    def set_weights(self, params) -> None:
        self.params = params

    def get_weights(self):
        return self.params

    def _explore_batch(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        """One policy forward over the whole env batch [N, ...]."""
        import jax

        if self._explore_fn is None:
            self._explore_fn = jax.jit(self.module.forward_exploration)
        self._rng, key = jax.random.split(self._rng)
        out = self._explore_fn(self.params, obs, key)
        return {k: np.asarray(v) for k, v in out.items()}

    def _infer_batch(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        """Greedy (deterministic) forward for evaluation."""
        import jax

        if getattr(self, "_infer_fn", None) is None:
            self._infer_fn = jax.jit(self.module.forward_inference)
        out = self._infer_fn(self.params, obs)
        return {k: np.asarray(v) for k, v in out.items()}

    def sample_episodes(self, num_episodes: int,
                        explore: bool = False) -> List[float]:
        """Run whole episodes and return their returns — the evaluation
        path (reference: evaluation env-runner groups driven by
        AlgorithmConfig.evaluation()). Greedy by default."""
        assert self.params is not None, "set_weights before sample"
        self.env.reset(seed=self.config.get("seed", 0) * 777 +
                       self.worker_index + 10_000)
        ep_ret = np.zeros(self.num_envs, np.float64)
        discrete = hasattr(self.env.action_space, "n")
        # Per-env quota — taking the first N episodes to FINISH across
        # parallel envs would bias the sample toward short (usually
        # low-return) episodes.
        quota = -(-num_episodes // self.num_envs)
        counts = np.zeros(self.num_envs, np.int64)
        done_returns: List[float] = []
        self._prev_dones = np.ones(self.num_envs, bool)  # fresh episodes
        for _ in range(100_000):  # hard cap; envs bound episode length
            obs = self._obs_in(self.env.current_obs)
            out = (self._explore_batch(obs) if explore
                   else self._infer_batch(obs))
            out = self._module_to_env(
                out, explore=explore,
                action_space_n=(self.env.action_space.n if discrete
                                else None),
                rng=self._np_rng)
            actions = np.asarray(out["actions"])
            if not discrete:
                actions = actions.astype(np.float32)
            _, rewards, terms, truncs = self.env.step(actions)
            self._prev_dones = terms | truncs
            ep_ret += rewards
            for i in np.nonzero(terms | truncs)[0]:
                if counts[i] < quota:
                    done_returns.append(float(ep_ret[i]))
                    counts[i] += 1
                ep_ret[i] = 0.0
            if len(done_returns) >= num_episodes:
                return done_returns[:num_episodes]
        return done_returns

    def sample(self, num_steps: int, explore: bool = True,
               epsilon: float = 0.0) -> SampleBatch:
        """Collect >= num_steps transitions (rounded up to a multiple of
        num_envs; episodes may span calls).

        epsilon > 0 overrides sampled actions with uniform-random ones
        (value-based algorithms; reference: EpsilonGreedy connector).
        The batch is laid out env-major (env0's steps, then env1's ...)
        so each eps_id segment is chronologically ordered for GAE.
        """
        assert self.params is not None, "set_weights before sample"
        n_iters = -(-num_steps // self.num_envs)
        discrete = hasattr(self.env.action_space, "n")
        per_env: List[Dict[str, List[Any]]] = [
            collections.defaultdict(list) for _ in range(self.num_envs)]
        last_terms = np.zeros(self.num_envs, bool)
        last_truncs = np.zeros(self.num_envs, bool)
        last_next_obs = self.env.current_obs
        for _ in range(n_iters):
            obs = self._obs_in(self.env.current_obs)
            out = self._explore_batch(obs)
            # module_to_env pipeline (default: EpsilonGreedy) — action
            # post-processing lives in connectors, not the runner.
            out = self._module_to_env(
                out, explore=explore, epsilon=epsilon,
                action_space_n=(self.env.action_space.n if discrete
                                else None),
                rng=self._np_rng)
            actions = np.asarray(out["actions"])
            next_obs_raw, rewards, terms, truncs = self.env.step(actions)
            done = terms | truncs
            # NEXT_OBS records the CONTINUING-episode view (shifted stack
            # + final obs) even on done steps: vector envs hand back the
            # ending episode's final obs here, and bootstrap values must
            # see the same stack the policy would have (the truncation
            # bootstrap below uses the identical no-dones peek).
            next_obs = self._obs_peek(next_obs_raw,
                                      np.zeros(self.num_envs, bool))
            for i in range(self.num_envs):
                cols = per_env[i]
                cols[sb.OBS].append(obs[i])
                cols[sb.NEXT_OBS].append(next_obs[i])
                cols[sb.ACTIONS].append(
                    int(actions[i]) if discrete
                    else np.asarray(actions[i], np.float32))
                cols[sb.REWARDS].append(float(rewards[i]))
                cols[sb.TERMINATEDS].append(bool(terms[i]))
                cols[sb.TRUNCATEDS].append(bool(truncs[i]))
                cols[sb.EPS_ID].append(int(self._eps_id[i]))
                if "action_logp" in out:
                    cols[sb.ACTION_LOGP].append(out["action_logp"][i])
                if "vf_preds" in out:
                    cols[sb.VF_PREDS].append(out["vf_preds"][i])
            self._episode_return += rewards
            self._total_steps += self.num_envs
            for i in np.nonzero(done)[0]:
                self._recent_returns.append(float(
                    self._episode_return[i]))
                self._episode_return[i] = 0.0
                self._eps_id[i] += 1
            last_terms, last_truncs = terms, truncs
            last_next_obs = next_obs_raw
            self._prev_dones = done
        # Exact per-env bootstraps for each env's final step: terminated
        # → 0; truncated → V(final next_obs); cut mid-episode →
        # V(current obs). Each batched forward runs only when some env
        # actually needs that bootstrap kind. Peek transforms: the value
        # net sees the same connector view the next forward would.
        zeros = np.zeros(self.num_envs, np.float32)
        no_dones = np.zeros(self.num_envs, bool)
        vf_next = (self._explore_batch(
            self._obs_peek(last_next_obs, no_dones)).get(
            "vf_preds", zeros) if last_truncs.any() else zeros)
        cut = ~(last_terms | last_truncs)
        vf_cur = (self._explore_batch(
            self._obs_peek(self.env.current_obs, self._prev_dones)).get(
            "vf_preds", zeros) if cut.any() else zeros)
        boots: Dict[int, float] = {}
        for i in range(self.num_envs):
            # The final step of env i belongs to eps_id recorded BEFORE
            # any post-step increment.
            final_eps = int(per_env[i][sb.EPS_ID][-1])
            if last_terms[i]:
                boots[final_eps] = 0.0
            elif last_truncs[i]:
                boots[final_eps] = float(np.asarray(vf_next)[i])
            else:
                boots[final_eps] = float(np.asarray(vf_cur)[i])
        self._end_bootstraps = boots
        merged: Dict[str, np.ndarray] = {}
        for key in per_env[0]:
            merged[key] = np.concatenate(
                [np.asarray(per_env[i][key])
                 for i in range(self.num_envs)])
        return SampleBatch(merged)

    def evaluate_perturbations(self, base_params, seeds: List[int],
                               stdev: float, episodes_per: int = 1
                               ) -> List[tuple]:
        """ES/ARS worker op (reference: rllib_contrib ES/ARS workers):
        for each noise seed, evaluate the antithetic pair
        theta ± stdev * eps(seed) and return (seed, ret_plus, ret_minus).

        Noise ships as SEEDS, not vectors — each side regenerates the
        same eps from the seed (the classic shared-noise-table trick,
        cheap on DCN). Episode returns are recorded into the runner's
        recent-returns window so standard metrics aggregation reflects
        the perturbation sweep.
        """
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        flat, unravel = ravel_pytree(base_params)
        flat = np.asarray(flat, np.float32)
        saved = self.params
        out = []
        try:
            for seed in seeds:
                eps = np.random.default_rng(int(seed)).standard_normal(
                    flat.shape[0]).astype(np.float32)
                pair = []
                for sign in (1.0, -1.0):
                    self.params = unravel(
                        jnp.asarray(flat + sign * stdev * eps))
                    rets = self.sample_episodes(episodes_per)
                    for r in rets:
                        self._recent_returns.append(float(r))
                    pair.append(float(np.mean(rets)) if rets else 0.0)
                out.append((int(seed), pair[0], pair[1]))
        finally:
            self.params = saved
        return out

    def bootstrap_value(self):
        """Per-final-episode value bootstraps of the last sample()
        rollout ({eps_id: value}, consumed by compute_gae). Scalar-like
        for num_envs==1 callers expecting the old contract is preserved
        by compute_gae accepting either form."""
        if hasattr(self, "_end_bootstraps"):
            return self._end_bootstraps
        out = self._explore_batch(
            self._obs_peek(self.env.current_obs, self._prev_dones))
        vals = np.asarray(out.get("vf_preds",
                                  np.zeros(self.num_envs, np.float32)))
        return {int(self._eps_id[i]): float(vals[i])
                for i in range(self.num_envs)}

    def get_metrics(self) -> Dict[str, Any]:
        returns = list(self._recent_returns)
        return {
            "episode_return_mean":
                float(np.mean(returns)) if returns else float("nan"),
            "num_episodes": len(returns),
            "num_env_steps": self._total_steps,
        }

    def ping(self) -> bool:
        return True
