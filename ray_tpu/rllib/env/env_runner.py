"""SingleAgentEnvRunner — samples episodes with the current policy.

Reference: rllib/env/single_agent_env_runner.py:60. Runs as a CPU actor:
holds the env + an RLModule evaluated eagerly from host weights (jit on
CPU backend), returns SampleBatches through the object store.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.env.registry import make_env
from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class SingleAgentEnvRunner:
    """One rollout worker. Methods are called via actor RPCs."""

    def __init__(self, config: dict, worker_index: int = 0):
        import jax

        self.config = config
        self.worker_index = worker_index
        self.env = make_env(config["env"], config.get("env_config"))
        spec = config["module_spec"]
        self.module = spec.build()
        self._rng = jax.random.PRNGKey(
            config.get("seed", 0) * 1000 + worker_index)
        self._np_rng = np.random.default_rng(
            config.get("seed", 0) * 1000 + worker_index)
        self.params = None
        self._obs, _ = self.env.reset(
            seed=config.get("seed", 0) * 1000 + worker_index)
        self._episode_return = 0.0
        self._episode_len = 0
        self._eps_id = worker_index * 1_000_000
        self._recent_returns: collections.deque = collections.deque(
            maxlen=100)
        self._explore_fn = None
        self._total_steps = 0

    def set_weights(self, params) -> None:
        self.params = params

    def get_weights(self):
        return self.params

    def _explore(self, obs: np.ndarray) -> Dict[str, np.ndarray]:
        import jax

        if self._explore_fn is None:
            self._explore_fn = jax.jit(self.module.forward_exploration)
        self._rng, key = jax.random.split(self._rng)
        out = self._explore_fn(self.params, obs[None, ...], key)
        return {k: np.asarray(v)[0] for k, v in out.items()}

    def sample(self, num_steps: int, explore: bool = True,
               epsilon: float = 0.0) -> SampleBatch:
        """Collect exactly num_steps transitions (episodes may span calls).

        epsilon > 0 overrides the sampled action with a uniform-random one
        (for value-based algorithms; reference: EpsilonGreedy connector).
        """
        assert self.params is not None, "set_weights before sample"
        cols: Dict[str, List[Any]] = collections.defaultdict(list)
        last_terminated = last_truncated = False
        last_next_obs = self._obs
        discrete = hasattr(self.env.action_space, "n")
        for _ in range(num_steps):
            out = self._explore(self._obs)
            if discrete:
                action = int(out["actions"])
                if epsilon > 0.0 and self._np_rng.random() < epsilon:
                    action = int(self._np_rng.integers(
                        self.env.action_space.n))
            else:  # continuous (Box): ship the action vector as-is
                action = np.asarray(out["actions"], np.float32)
            next_obs, reward, terminated, truncated, _ = self.env.step(
                action)
            cols[sb.OBS].append(self._obs)
            cols[sb.NEXT_OBS].append(next_obs)
            cols[sb.ACTIONS].append(action)
            cols[sb.REWARDS].append(reward)
            cols[sb.TERMINATEDS].append(terminated)
            cols[sb.TRUNCATEDS].append(truncated)
            cols[sb.EPS_ID].append(self._eps_id)
            if "action_logp" in out:
                cols[sb.ACTION_LOGP].append(out["action_logp"])
            if "vf_preds" in out:
                cols[sb.VF_PREDS].append(out["vf_preds"])
            self._episode_return += reward
            self._episode_len += 1
            self._total_steps += 1
            last_terminated, last_truncated = terminated, truncated
            last_next_obs = next_obs
            if terminated or truncated:
                self._recent_returns.append(self._episode_return)
                self._episode_return = 0.0
                self._episode_len = 0
                self._eps_id += 1
                self._obs, _ = self.env.reset()
            else:
                self._obs = next_obs
        # Exact bootstrap for this rollout's final step (computed BEFORE
        # the post-reset obs can leak in): terminated → 0; truncated →
        # V(final next_obs); cut mid-episode → V(current obs).
        if last_terminated:
            self._end_bootstrap = 0.0
        elif last_truncated:
            out = self._explore(last_next_obs)
            self._end_bootstrap = float(out.get("vf_preds", 0.0))
        else:
            out = self._explore(self._obs)
            self._end_bootstrap = float(out.get("vf_preds", 0.0))
        return SampleBatch({
            k: np.asarray(v) for k, v in cols.items()})

    def bootstrap_value(self) -> float:
        """Value bootstrap for the last sample() rollout's final step —
        used by GAE (see sample() for the terminated/truncated cases)."""
        if hasattr(self, "_end_bootstrap"):
            return self._end_bootstrap
        out = self._explore(self._obs)
        return float(out.get("vf_preds", 0.0))

    def get_metrics(self) -> Dict[str, Any]:
        returns = list(self._recent_returns)
        return {
            "episode_return_mean":
                float(np.mean(returns)) if returns else float("nan"),
            "num_episodes": len(returns),
            "num_env_steps": self._total_steps,
        }

    def ping(self) -> bool:
        return True
