"""EnvRunnerGroup — manages N env-runner actors.

Reference: rllib/env/env_runner_group.py:71 + the synchronous_parallel_
sample util (rllib/algorithms/ppo/ppo.py:441 uses it). Runners are CPU
actors; weights ship via the object store (one put, N gets).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class EnvRunnerGroup:
    def __init__(self, config: dict, runner_cls: type = None):
        self.config = config
        runner_cls = runner_cls or SingleAgentEnvRunner
        self.num_remote = int(config.get("num_env_runners", 0))
        cpus_per_runner = config.get("num_cpus_per_env_runner", 1)
        self._local_runner = None
        self._manager: Optional[FaultTolerantActorManager] = None
        if self.num_remote == 0:
            self._local_runner = runner_cls(config, 0)
        else:
            cls = ray_tpu.remote(runner_cls)

            def factory(i: int):
                return cls.options(
                    num_cpus=cpus_per_runner,
                    max_restarts=config.get("max_restarts", 1),
                ).remote(config, i + 1)

            actors = [factory(i) for i in range(self.num_remote)]
            self._manager = FaultTolerantActorManager(actors, factory)

    # ---- weights ----

    def sync_weights(self, params) -> None:
        if self._local_runner is not None:
            self._local_runner.set_weights(params)
            return
        ref = ray_tpu.put(params)
        self._manager.foreach(lambda a: a.set_weights.remote(ref))

    # ---- sampling ----

    def sample(self, total_steps: int,
               epsilon: float = 0.0) -> SampleBatch:
        """Synchronous parallel sample: each healthy runner collects an
        equal share of total_steps."""
        batches = [b for b, _ in
                   self.sample_with_bootstraps(total_steps, epsilon)]
        return SampleBatch.concat_samples(batches)

    def sample_with_bootstraps(self, total_steps: int, epsilon: float = 0.0
                               ) -> List[tuple]:
        """Returns [(batch, bootstrap_value)] per healthy runner — the
        bootstrap is that runner's exact value estimate for its rollout's
        final step (GAE needs it per-runner, not averaged)."""
        if self._local_runner is not None:
            batch = self._local_runner.sample(total_steps, epsilon=epsilon)
            return [(batch, self._local_runner.bootstrap_value())]
        n = max(1, self._manager.num_healthy_actors())
        per_runner = max(1, total_steps // n)
        results = self._manager.foreach(
            lambda a: a.sample.remote(per_runner, epsilon=epsilon))
        out = []
        for i, batch in results.ok:
            try:
                boot = ray_tpu.get(
                    self._manager.actor(i).bootstrap_value.remote(),
                    timeout=30.0)
            except Exception:
                boot = 0.0
            out.append((batch, boot))
        if not out:
            raise RuntimeError("all env runners failed during sample()")
        return out

    def sample_multi(self, total_steps: int) -> List[tuple]:
        """Multi-agent variant (runner_cls=MultiAgentEnvRunner): returns
        [(per_module_batches, per_agent_bootstraps)] per healthy runner."""
        if self._local_runner is not None:
            batches = self._local_runner.sample(total_steps)
            return [(batches, self._local_runner.bootstrap_values())]
        n = max(1, self._manager.num_healthy_actors())
        per_runner = max(1, total_steps // n)
        results = self._manager.foreach(
            lambda a: a.sample.remote(per_runner))
        out = []
        for i, batches in results.ok:
            try:
                boots = ray_tpu.get(
                    self._manager.actor(i).bootstrap_values.remote(),
                    timeout=30.0)
            except Exception:
                boots = {}
            out.append((batches, boots))
        if not out:
            raise RuntimeError("all env runners failed during sample()")
        return out

    def sample_episodes(self, num_episodes: int,
                        explore: bool = False) -> List[float]:
        """Whole-episode returns across runners (evaluation path)."""
        if self._local_runner is not None:
            return self._local_runner.sample_episodes(num_episodes,
                                                      explore=explore)
        n = max(1, self._manager.num_healthy_actors())
        per = -(-num_episodes // n)
        results = self._manager.foreach(
            lambda a: a.sample_episodes.remote(per, explore=explore))
        out: List[float] = []
        for _, returns in results.ok:
            out.extend(returns)
        return out[:num_episodes] if out else []

    def evaluate_perturbations(self, params, seeds: List[int],
                               stdev: float,
                               episodes_per: int = 1) -> List[tuple]:
        """ES/ARS fan-out: shard the seed list round-robin over healthy
        runners; each evaluates its antithetic pairs. Failed runners'
        shards are dropped for the iteration (gradient-free updates
        tolerate missing directions)."""
        if self._local_runner is not None:
            return self._local_runner.evaluate_perturbations(
                params, list(seeds), stdev, episodes_per)
        ids = self._manager.healthy_actor_ids()
        if not ids:
            raise RuntimeError("no healthy env runners")
        shards: Dict[int, List[int]] = {i: [] for i in ids}
        for k, s in enumerate(seeds):
            shards[ids[k % len(ids)]].append(int(s))
        ref = ray_tpu.put(params)
        results = self._manager.foreach_sharded(
            lambda a, shard: a.evaluate_perturbations.remote(
                ref, shard, stdev, episodes_per),
            {i: shard for i, shard in shards.items() if shard})
        out: List[tuple] = []
        for _, pairs in results.ok:
            out.extend(pairs)
        if not out:
            raise RuntimeError(
                "all env runners failed during evaluate_perturbations()")
        return out

    # ---- health / metrics ----

    def restore_failed(self, params_fn=None) -> int:
        """params_fn: zero-arg callable producing current weights — only
        invoked when an actor was actually restored (weight pulls are a
        full device→host transfer; don't pay per-iteration)."""
        if self._manager is None:
            return 0
        restored = self._manager.probe_unhealthy()
        if restored and params_fn is not None:
            ref = ray_tpu.put(params_fn())
            for i in restored:
                ray_tpu.get(self._manager.actor(i).set_weights.remote(ref))
        return len(restored)

    def num_healthy(self) -> int:
        if self._local_runner is not None:
            return 1
        return self._manager.num_healthy_actors()

    def aggregate_metrics(self) -> Dict[str, Any]:
        if self._local_runner is not None:
            metrics = [self._local_runner.get_metrics()]
        else:
            metrics = self._manager.foreach(
                lambda a: a.get_metrics.remote()).values()
        if not metrics:
            return {}
        returns = [m["episode_return_mean"] for m in metrics
                   if m["num_episodes"] > 0]
        return {
            "episode_return_mean":
                float(np.mean(returns)) if returns else float("nan"),
            "num_episodes": sum(m["num_episodes"] for m in metrics),
            "num_env_steps": sum(m["num_env_steps"] for m in metrics),
            "num_healthy_env_runners": self.num_healthy(),
        }

    def stop(self) -> None:
        if self._manager is not None:
            for i in list(self._manager._actors):
                try:
                    ray_tpu.kill(self._manager.actor(i))
                except Exception:
                    pass
