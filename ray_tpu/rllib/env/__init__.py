"""Env runners and built-in envs."""
