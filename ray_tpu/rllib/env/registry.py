"""Environment registry.

Reference: ray.tune.registry.register_env (used by RLlib configs to map a
string env id to a creator). Built-ins resolve first; unknown ids fall
back to gymnasium when it is importable.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

_REGISTRY: Dict[str, Callable] = {}


def register_env(name: str, creator: Callable) -> None:
    _REGISTRY[name] = creator


def _builtin(name: str) -> Optional[Callable]:
    from ray_tpu.rllib.env.multi_agent_env import TwoAgentGrid
    from ray_tpu.rllib.env.tiny_envs import CartPole, GridWorld, Pendulum

    table = {
        "CartPole-v1": CartPole,
        "CartPole": CartPole,
        "GridWorld-v0": GridWorld,
        "GridWorld": GridWorld,
        "Pendulum-v1": Pendulum,
        "Pendulum": Pendulum,
        "TwoAgentGrid": TwoAgentGrid,
    }
    return table.get(name)


def make_env(env: object, env_config: Optional[dict] = None):
    """Instantiate an env from an id string, creator callable, or class."""
    env_config = env_config or {}
    if callable(env):
        return env(env_config)
    if isinstance(env, str):
        creator = _REGISTRY.get(env) or _builtin(env)
        if creator is not None:
            return creator(env_config)
        try:
            import gymnasium

            return gymnasium.make(env, **env_config)
        except Exception:
            raise ValueError(
                f"unknown env id {env!r}: not registered, not a built-in, "
                "and gymnasium could not create it")
    raise TypeError(f"env must be a str id or callable, got {type(env)}")
