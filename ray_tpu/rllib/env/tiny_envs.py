"""Built-in numpy environments (no external gym dependency).

The reference leans on gymnasium for its test envs; this framework ships
tiny in-repo versions with the gymnasium step/reset API so RL tests run
anywhere. External gymnasium envs plug in through the same registry
(see registry.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


class Box:
    """Minimal space descriptor (continuous)."""

    def __init__(self, low, high, shape, dtype=np.float32):
        self.low = low
        self.high = high
        self.shape = tuple(shape)
        self.dtype = dtype


class Discrete:
    """Minimal space descriptor (categorical actions)."""

    def __init__(self, n: int):
        self.n = int(n)
        self.shape = ()
        self.dtype = np.int64


class CartPole:
    """Classic cart-pole balance task (dynamics per Barto-Sutton-Anderson,
    matching gymnasium's CartPole-v1: 500-step limit, +1 reward/step)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    observation_space = Box(-np.inf, np.inf, (4,))
    action_space = Discrete(2)

    def __init__(self, config: Optional[dict] = None):
        self._rng = np.random.default_rng(0)
        self._state = np.zeros(4, np.float32)
        self._steps = 0

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[np.ndarray, Dict[str, Any]]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._steps = 0
        return self._state.copy(), {}

    def step(self, action: int
             ) -> Tuple[np.ndarray, float, bool, bool, Dict[str, Any]]:
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN *
            (4.0 / 3.0 - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._steps += 1
        terminated = bool(abs(x) > self.X_LIMIT or
                          abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        return self._state.copy(), 1.0, terminated, truncated, {}


class Pendulum:
    """Classic underactuated pendulum swing-up (dynamics and reward match
    gymnasium's Pendulum-v1: obs [cos th, sin th, thdot], torque in
    [-2, 2], reward -(th^2 + 0.1 thdot^2 + 0.001 u^2), 200-step episodes).
    The canonical continuous-control test task (for SAC)."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    observation_space = Box(-np.inf, np.inf, (3,))
    action_space = Box(-MAX_TORQUE, MAX_TORQUE, (1,))

    def __init__(self, config: Optional[dict] = None):
        self._rng = np.random.default_rng(0)
        self._th = 0.0
        self._thdot = 0.0
        self._steps = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._th), np.sin(self._th),
                         self._thdot], np.float32)

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = self._rng.uniform(-np.pi, np.pi)
        self._thdot = self._rng.uniform(-1.0, 1.0)
        self._steps = 0
        return self._obs(), {}

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -self.MAX_TORQUE, self.MAX_TORQUE))
        th_norm = ((self._th + np.pi) % (2 * np.pi)) - np.pi
        cost = th_norm ** 2 + 0.1 * self._thdot ** 2 + 0.001 * u ** 2
        thdot = self._thdot + (
            3 * self.G / (2 * self.L) * np.sin(self._th) +
            3.0 / (self.M * self.L ** 2) * u) * self.DT
        self._thdot = float(np.clip(thdot, -self.MAX_SPEED, self.MAX_SPEED))
        self._th = self._th + self._thdot * self.DT
        self._steps += 1
        truncated = self._steps >= self.MAX_STEPS
        return self._obs(), -float(cost), False, truncated, {}


class GridWorld:
    """N×N grid; start top-left, goal bottom-right; -0.01/step, -0.05 for
    bumping a wall, +1 at the goal.

    Observation is the one-hot cell index; actions: 0=up 1=right 2=down
    3=left. The wall penalty breaks the Q-value tie between a no-op bump
    and progress, so the greedy policy is unambiguous under function
    approximation. Useful for DQN tests (tabular-ish, fast convergence).
    """

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.n = int(config.get("size", 4))
        self.max_steps = int(config.get("max_steps", 4 * self.n * self.n))
        self.observation_space = Box(0.0, 1.0, (self.n * self.n,))
        self.action_space = Discrete(4)
        self._pos = 0
        self._steps = 0
        self._rng = np.random.default_rng(0)

    def _obs(self) -> np.ndarray:
        obs = np.zeros(self.n * self.n, np.float32)
        obs[self._pos] = 1.0
        return obs

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._pos = 0
        self._steps = 0
        return self._obs(), {}

    def step(self, action: int):
        prev = self._pos
        row, col = divmod(self._pos, self.n)
        if action == 0:
            row = max(0, row - 1)
        elif action == 1:
            col = min(self.n - 1, col + 1)
        elif action == 2:
            row = min(self.n - 1, row + 1)
        elif action == 3:
            col = max(0, col - 1)
        self._pos = row * self.n + col
        self._steps += 1
        at_goal = self._pos == self.n * self.n - 1
        if at_goal:
            reward = 1.0
        elif self._pos == prev:
            reward = -0.05
        else:
            reward = -0.01
        truncated = self._steps >= self.max_steps
        return self._obs(), reward, at_goal, truncated, {}
