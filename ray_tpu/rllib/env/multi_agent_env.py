"""Multi-agent environment protocol + a tiny built-in test env.

Reference: rllib/env/multi_agent_env.py (MultiAgentEnv: dict-keyed
reset/step — {agent_id: obs}, {agent_id: reward}, ... with "__all__" in
the done dicts). Agents may come and go between steps; each agent maps
to a policy module via the algorithm's policy_mapping_fn.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.env.tiny_envs import Box, Discrete, GridWorld


class MultiAgentEnv:
    """Protocol: subclasses define agents, observation/action spaces per
    agent, and dict-keyed reset/step."""

    agent_ids: Tuple[str, ...] = ()

    def observation_space_of(self, agent_id: str):
        raise NotImplementedError

    def action_space_of(self, agent_id: str):
        raise NotImplementedError

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        """Returns (obs, rewards, terminateds, truncateds, infos), each a
        dict keyed by agent id; terminateds/truncateds also carry
        "__all__"."""
        raise NotImplementedError


class CoopPress(MultiAgentEnv):
    """Cooperative coordination task (QMIX testbed): each step both
    agents observe a context bit and must JOINTLY act — both matching
    the context pays +1, both pressing the other button +0.3, any
    mismatch 0. The reward is a single TEAM reward (shared), so
    credit assignment needs centralized value decomposition.
    """

    agent_ids = ("a0", "a1")

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self.episode_len = int(config.get("episode_len", 8))
        self._rng = np.random.default_rng(config.get("seed", 0))
        self._ctx = 0
        self._t = 0

    def observation_space_of(self, agent_id: str):
        return Box(0.0, 1.0, (2,))

    def action_space_of(self, agent_id: str):
        return Discrete(2)

    def _obs(self) -> Dict[str, np.ndarray]:
        o = np.zeros(2, np.float32)
        o[self._ctx] = 1.0
        return {a: o.copy() for a in self.agent_ids}

    def reset(self, *, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._ctx = int(self._rng.integers(2))
        self._t = 0
        return self._obs(), {}

    def step(self, actions: Dict[str, Any]):
        a0, a1 = int(actions["a0"]), int(actions["a1"])
        if a0 == a1 == self._ctx:
            team = 1.0
        elif a0 == a1:
            team = 0.3
        else:
            team = 0.0
        self._t += 1
        self._ctx = int(self._rng.integers(2))
        done = self._t >= self.episode_len
        obs = self._obs()
        rewards = {a: team for a in self.agent_ids}
        terms = {a: False for a in self.agent_ids}
        terms["__all__"] = False
        truncs = {a: done for a in self.agent_ids}
        truncs["__all__"] = done
        return obs, rewards, terms, truncs, {}


class TwoAgentGrid(MultiAgentEnv):
    """Two independent GridWorld agents on separate boards, one episode
    clock. Agent "a1"'s board is larger than "a0"'s, so the two policies
    genuinely need different weights — a 2-policy smoke env.
    """

    agent_ids = ("a0", "a1")

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self._envs = {
            "a0": GridWorld({"size": config.get("size_a0", 3)}),
            "a1": GridWorld({"size": config.get("size_a1", 4)}),
        }
        self._terminated: Dict[str, bool] = {}
        self._truncated: Dict[str, bool] = {}

    def observation_space_of(self, agent_id: str):
        return self._envs[agent_id].observation_space

    def action_space_of(self, agent_id: str):
        return self._envs[agent_id].action_space

    def reset(self, *, seed: Optional[int] = None):
        obs, infos = {}, {}
        for aid, env in self._envs.items():
            o, i = env.reset(seed=seed)
            obs[aid] = o
            infos[aid] = i
        self._terminated = {aid: False for aid in self.agent_ids}
        self._truncated = {aid: False for aid in self.agent_ids}
        return obs, infos

    def step(self, actions: Dict[str, Any]):
        obs: Dict[str, np.ndarray] = {}
        rewards: Dict[str, float] = {}
        terminateds: Dict[str, bool] = {}
        truncateds: Dict[str, bool] = {}
        for aid, action in actions.items():
            if self._terminated.get(aid) or self._truncated.get(aid):
                continue
            o, r, term, trunc, _ = self._envs[aid].step(action)
            obs[aid] = o
            rewards[aid] = r
            terminateds[aid] = term
            truncateds[aid] = trunc
            self._terminated[aid] = term
            self._truncated[aid] = trunc and not term
        all_done = all(t or u for t, u in zip(self._terminated.values(),
                                              self._truncated.values()))
        # A natural all-agents termination is NOT a truncation: consumers
        # use the distinction to decide final-step bootstrapping.
        terminateds["__all__"] = all_done and \
            all(self._terminated.values())
        truncateds["__all__"] = all_done and \
            not all(self._terminated.values())
        return obs, rewards, terminateds, truncateds, {}
