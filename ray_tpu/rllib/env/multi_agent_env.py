"""Multi-agent environment protocol + a tiny built-in test env.

Reference: rllib/env/multi_agent_env.py (MultiAgentEnv: dict-keyed
reset/step — {agent_id: obs}, {agent_id: reward}, ... with "__all__" in
the done dicts). Agents may come and go between steps; each agent maps
to a policy module via the algorithm's policy_mapping_fn.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.env.tiny_envs import Box, Discrete, GridWorld


class MultiAgentEnv:
    """Protocol: subclasses define agents, observation/action spaces per
    agent, and dict-keyed reset/step."""

    agent_ids: Tuple[str, ...] = ()

    def observation_space_of(self, agent_id: str):
        raise NotImplementedError

    def action_space_of(self, agent_id: str):
        raise NotImplementedError

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, actions: Dict[str, Any]):
        """Returns (obs, rewards, terminateds, truncateds, infos), each a
        dict keyed by agent id; terminateds/truncateds also carry
        "__all__"."""
        raise NotImplementedError


class TwoAgentGrid(MultiAgentEnv):
    """Two independent GridWorld agents on separate boards, one episode
    clock. Agent "a1"'s board is larger than "a0"'s, so the two policies
    genuinely need different weights — a 2-policy smoke env.
    """

    agent_ids = ("a0", "a1")

    def __init__(self, config: Optional[dict] = None):
        config = config or {}
        self._envs = {
            "a0": GridWorld({"size": config.get("size_a0", 3)}),
            "a1": GridWorld({"size": config.get("size_a1", 4)}),
        }
        self._terminated: Dict[str, bool] = {}
        self._truncated: Dict[str, bool] = {}

    def observation_space_of(self, agent_id: str):
        return self._envs[agent_id].observation_space

    def action_space_of(self, agent_id: str):
        return self._envs[agent_id].action_space

    def reset(self, *, seed: Optional[int] = None):
        obs, infos = {}, {}
        for aid, env in self._envs.items():
            o, i = env.reset(seed=seed)
            obs[aid] = o
            infos[aid] = i
        self._terminated = {aid: False for aid in self.agent_ids}
        self._truncated = {aid: False for aid in self.agent_ids}
        return obs, infos

    def step(self, actions: Dict[str, Any]):
        obs: Dict[str, np.ndarray] = {}
        rewards: Dict[str, float] = {}
        terminateds: Dict[str, bool] = {}
        truncateds: Dict[str, bool] = {}
        for aid, action in actions.items():
            if self._terminated.get(aid) or self._truncated.get(aid):
                continue
            o, r, term, trunc, _ = self._envs[aid].step(action)
            obs[aid] = o
            rewards[aid] = r
            terminateds[aid] = term
            truncateds[aid] = trunc
            self._terminated[aid] = term
            self._truncated[aid] = trunc and not term
        all_done = all(t or u for t, u in zip(self._terminated.values(),
                                              self._truncated.values()))
        # A natural all-agents termination is NOT a truncation: consumers
        # use the distinction to decide final-step bootstrapping.
        terminateds["__all__"] = all_done and \
            all(self._terminated.values())
        truncateds["__all__"] = all_done and \
            not all(self._terminated.values())
        return obs, rewards, terminateds, truncateds, {}
