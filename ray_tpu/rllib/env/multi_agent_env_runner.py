"""MultiAgentEnvRunner — samples a multi-agent env with per-policy modules.

Reference: rllib/env/multi_agent_env_runner.py:54 (MultiAgentEnvRunner:
one env, N agents, policy_mapping_fn agent_id -> module_id, per-module
batch assembly). Runs as a CPU actor exactly like SingleAgentEnvRunner;
sample() returns {module_id: SampleBatch}.
"""

from __future__ import annotations

import collections
from typing import Any, Dict, List

import numpy as np

from ray_tpu.rllib.env.registry import make_env
from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class MultiAgentEnvRunner:
    """One multi-agent rollout worker. Methods are actor RPCs."""

    def __init__(self, config: dict, worker_index: int = 0):
        import jax

        self.config = config
        self.worker_index = worker_index
        self.env = make_env(config["env"], config.get("env_config"))
        self.policy_mapping_fn = config.get(
            "policy_mapping_fn") or (lambda aid: aid)
        # module_specs: {module_id: RLModuleSpec}
        self.modules = {mid: spec.build()
                        for mid, spec in config["module_specs"].items()}
        self.params: Dict[str, Any] = {}
        self._explore_fns: Dict[str, Any] = {}
        self._rng = jax.random.PRNGKey(
            config.get("seed", 0) * 1000 + worker_index)
        self._obs, _ = self.env.reset(
            seed=config.get("seed", 0) * 1000 + worker_index)
        self._episode_returns: Dict[str, float] = collections.defaultdict(
            float)
        self._recent_returns: collections.deque = collections.deque(
            maxlen=100)
        # Per-AGENT episode ids: a shared-policy module concatenates
        # several agents' trajectories, and GAE relies on eps_id changes
        # to find trajectory boundaries.
        self._eps_ids = {
            aid: worker_index * 1_000_000 + j * 100_000
            for j, aid in enumerate(self.env.agent_ids)}
        self._total_steps = 0

    def set_weights(self, params: Dict[str, Any]) -> None:
        self.params = params

    def _explore(self, module_id: str, obs) -> Dict[str, np.ndarray]:
        import jax

        if module_id not in self._explore_fns:
            self._explore_fns[module_id] = jax.jit(
                self.modules[module_id].forward_exploration)
        self._rng, key = jax.random.split(self._rng)
        out = self._explore_fns[module_id](
            self.params[module_id], obs[None, ...], key)
        return {k: np.asarray(v)[0] for k, v in out.items()}

    def sample(self, num_env_steps: int
               ) -> Dict[str, Dict[str, SampleBatch]]:
        """Collect num_env_steps env steps.

        Returns {module_id: {agent_id: SampleBatch}} — per-AGENT batches
        so the trainer can GAE each agent's trajectory with its own
        bootstrap before concatenating a shared module's data."""
        assert self.params, "set_weights before sample"
        cols: Dict[str, Dict[str, List[Any]]] = collections.defaultdict(
            lambda: collections.defaultdict(list))
        for _ in range(num_env_steps):
            actions: Dict[str, Any] = {}
            step_outs: Dict[str, Dict[str, np.ndarray]] = {}
            for aid, obs in self._obs.items():
                mid = self.policy_mapping_fn(aid)
                out = self._explore(mid, obs)
                step_outs[aid] = out
                discrete = hasattr(self.env.action_space_of(aid), "n")
                actions[aid] = (int(out["actions"]) if discrete
                                else np.asarray(out["actions"]))
            next_obs, rewards, terms, truncs, _ = self.env.step(actions)
            for aid in actions:
                if aid not in rewards:
                    continue
                c = cols[aid]
                c[sb.OBS].append(self._obs[aid])
                c[sb.NEXT_OBS].append(next_obs.get(aid, self._obs[aid]))
                c[sb.ACTIONS].append(actions[aid])
                c[sb.REWARDS].append(rewards[aid])
                c[sb.TERMINATEDS].append(terms.get(aid, False))
                c[sb.TRUNCATEDS].append(truncs.get(aid, False))
                c[sb.EPS_ID].append(self._eps_ids[aid])
                out = step_outs[aid]
                if "action_logp" in out:
                    c[sb.ACTION_LOGP].append(out["action_logp"])
                if "vf_preds" in out:
                    c[sb.VF_PREDS].append(out["vf_preds"])
                self._episode_returns[aid] += rewards[aid]
            self._total_steps += 1
            if terms.get("__all__") or truncs.get("__all__"):
                self._recent_returns.append(
                    sum(self._episode_returns.values()))
                self._episode_returns.clear()
                for aid in self._eps_ids:
                    self._eps_ids[aid] += 1
                self._obs, _ = self.env.reset()
            else:
                self._obs = {aid: o for aid, o in next_obs.items()}
        result: Dict[str, Dict[str, SampleBatch]] = \
            collections.defaultdict(dict)
        for aid, c in cols.items():
            mid = self.policy_mapping_fn(aid)
            result[mid][aid] = SampleBatch(
                {k: np.asarray(v) for k, v in c.items()})
        return dict(result)

    def bootstrap_values(self) -> Dict[str, float]:
        """Per-AGENT value bootstrap for the current (mid-episode) obs."""
        out: Dict[str, float] = {}
        for aid, obs in self._obs.items():
            mid = self.policy_mapping_fn(aid)
            o = self._explore(mid, obs)
            out[aid] = float(o.get("vf_preds", 0.0))
        return out

    def get_metrics(self) -> Dict[str, Any]:
        returns = list(self._recent_returns)
        return {
            "episode_return_mean":
                float(np.mean(returns)) if returns else float("nan"),
            "num_episodes": len(returns),
            "num_env_steps": self._total_steps,
        }

    def ping(self) -> bool:
        return True
