"""Vectorized environments — batch N sub-envs per runner.

Reference: rllib/env/single_agent_env_runner.py:60 (env runners step
gymnasium *vector* envs, not single envs). Two layers here:

- ``VectorEnv``: generic wrapper stepping N independent sub-envs with
  gymnasium-style autoreset (a sub-env that ends is reset immediately;
  ``step`` returns the PRE-reset next_obs so bootstrapping sees the true
  terminal observation, while ``current_obs`` advances to the reset one).
- ``VectorCartPole``: natively numpy-vectorized CartPole — one
  [N, 4] state array, all dynamics as array ops. This is the
  throughput-tier path (no per-env Python loop at all).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.env.tiny_envs import Box, Discrete


class VectorEnv:
    """N sub-envs with batched step/reset + autoreset."""

    VECTORIZED = True

    def __init__(self, make_fn: Callable[[], Any], num_envs: int,
                 seed: int = 0, first_env: Optional[Any] = None):
        self.envs = ([first_env] if first_env is not None else []) + \
            [make_fn() for _ in range(num_envs -
                                      (1 if first_env is not None else 0))]
        self.num_envs = num_envs
        self.observation_space = self.envs[0].observation_space
        self.action_space = self.envs[0].action_space
        self._seed = seed
        self._obs: Optional[np.ndarray] = None

    def reset(self, *, seed: Optional[int] = None) -> Tuple[np.ndarray,
                                                            dict]:
        base = self._seed if seed is None else seed
        obs = [e.reset(seed=base + i)[0]
               for i, e in enumerate(self.envs)]
        self._obs = np.stack(obs)
        return self._obs, {}

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     np.ndarray]:
        """Returns (next_obs_pre_reset, rewards, terminateds, truncateds);
        ended sub-envs are autoreset and current_obs reflects that."""
        next_obs: List[np.ndarray] = []
        cur_obs: List[np.ndarray] = []
        rewards = np.zeros(self.num_envs, np.float32)
        terms = np.zeros(self.num_envs, bool)
        truncs = np.zeros(self.num_envs, bool)
        for i, env in enumerate(self.envs):
            o, r, te, tr, _ = env.step(actions[i])
            next_obs.append(o)
            rewards[i] = r
            terms[i] = te
            truncs[i] = tr
            cur_obs.append(env.reset()[0] if (te or tr) else o)
        self._obs = np.stack(cur_obs)
        return np.stack(next_obs), rewards, terms, truncs

    @property
    def current_obs(self) -> np.ndarray:
        return self._obs


class VectorCartPole:
    """Numpy-vectorized CartPole: all N poles advance in one array op
    (dynamics identical to tiny_envs.CartPole / gymnasium CartPole-v1)."""

    VECTORIZED = True
    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    observation_space = Box(-np.inf, np.inf, (4,))
    action_space = Discrete(2)

    def __init__(self, num_envs: int, seed: int = 0,
                 config: Optional[dict] = None):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), np.float32)
        self._steps = np.zeros(num_envs, np.int64)

    def reset(self, *, seed: Optional[int] = None) -> Tuple[np.ndarray,
                                                            dict]:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(
            -0.05, 0.05, (self.num_envs, 4)).astype(np.float32)
        self._steps[:] = 0
        return self._state.copy(), {}

    def _reset_rows(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if n:
            self._state[mask] = self._rng.uniform(
                -0.05, 0.05, (n, 4)).astype(np.float32)
            self._steps[mask] = 0

    def step(self, actions) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     np.ndarray]:
        a = np.asarray(actions)
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(a == 1, self.FORCE_MAG, -self.FORCE_MAG)
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN *
            (4.0 / 3.0 - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot],
                               axis=1).astype(np.float32)
        self._steps += 1
        terms = (np.abs(x) > self.X_LIMIT) | \
            (np.abs(theta) > self.THETA_LIMIT)
        truncs = (~terms) & (self._steps >= self.MAX_STEPS)
        rewards = np.ones(self.num_envs, np.float32)
        next_obs = self._state.copy()
        self._reset_rows(terms | truncs)
        return next_obs, rewards, terms, truncs

    @property
    def current_obs(self) -> np.ndarray:
        return self._state.copy()


def make_vector_env(env: object, env_config: Optional[dict],
                    num_envs: int, seed: int = 0):
    """Vectorized env factory: natively-vectorized fast path when the
    name resolves to the BUILT-IN CartPole (a user registration of the
    same name takes precedence and gets the generic wrapper), generic
    VectorEnv wrapper otherwise."""
    from ray_tpu.rllib.env.registry import _REGISTRY, make_env

    if num_envs > 1 and isinstance(env, str) and \
            env.lower() in ("cartpole", "cartpole-v1") and \
            env not in _REGISTRY:
        return VectorCartPole(num_envs, seed=seed, config=env_config)
    probe = make_env(env, env_config)
    if getattr(probe, "VECTORIZED", False):
        return probe
    # The probe becomes sub-env 0 — expensive envs build exactly N times.
    return VectorEnv(lambda: make_env(env, env_config), num_envs,
                     seed=seed, first_env=probe)
