"""ray_tpu.rllib — reinforcement learning library.

Parity target: the reference's rllib/ new API stack (AlgorithmConfig /
Algorithm / EnvRunnerGroup / RLModule / Learner / LearnerGroup) with
JAX/TPU learners and CPU env-runner actors. Algorithms: PPO (single and
multi-agent), APPO, DQN, SAC, CQL, IMPALA, BC, MARWIL, DDPG, TD3, A2C, QMIX
(cooperative multi-agent value decomposition), AlphaZero (self-play
MCTS), DreamerV3 (model-based), ES, ARS (evolution).
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.cql import CQL, CQLConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.ddpg import (DDPG, DDPGConfig, TD3,
                                           TD3Config)
from ray_tpu.rllib.algorithms.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.algorithms.a2c import A2C, A2CConfig
from ray_tpu.rllib.algorithms.alphazero import AlphaZero, AlphaZeroConfig
from ray_tpu.rllib.algorithms.es import ARS, ARSConfig, ES, ESConfig
from ray_tpu.rllib.algorithms.qmix import QMIX, QMIXConfig
from ray_tpu.rllib.algorithms.multi_agent_ppo import (MultiAgentPPO,
                                                      MultiAgentPPOConfig)
from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv
from ray_tpu.rllib.env.registry import register_env

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "PPO",
    "PPOConfig",
    "DQN",
    "DQNConfig",
    "IMPALA",
    "BC",
    "BCConfig",
    "IMPALAConfig",
    "SAC",
    "SACConfig",
    "APPO",
    "APPOConfig",
    "CQL",
    "CQLConfig",
    "MARWIL",
    "MARWILConfig",
    "A2C",
    "A2CConfig",
    "AlphaZero",
    "AlphaZeroConfig",
    "DDPG",
    "DDPGConfig",
    "TD3",
    "TD3Config",
    "DreamerV3",
    "DreamerV3Config",
    "ES",
    "ESConfig",
    "ARS",
    "ARSConfig",
    "QMIX",
    "QMIXConfig",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "MultiAgentEnv",
    "register_env",
]
