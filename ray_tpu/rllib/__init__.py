"""ray_tpu.rllib — reinforcement learning library.

Parity target: the reference's rllib/ new API stack (AlgorithmConfig /
Algorithm / EnvRunnerGroup / RLModule / Learner / LearnerGroup) with
JAX/TPU learners and CPU env-runner actors. Algorithms: PPO, DQN.
"""

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.env.registry import register_env

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "PPO",
    "PPOConfig",
    "DQN",
    "DQNConfig",
    "IMPALA",
    "BC",
    "BCConfig",
    "IMPALAConfig",
    "register_env",
]
