"""Offline experience IO.

Reference: rllib/offline/ (json_writer.py / json_reader.py — sample
batches as JSON-lines files; dataset-based offline input for
BC/MARWIL/CQL). Arrays are stored column-wise per batch with base64
numpy payloads (exact dtype/shape roundtrip, unlike float-text JSON).
"""

from __future__ import annotations

import base64
import glob as _glob
import io
import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


def _encode(arr: np.ndarray) -> dict:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return {"__npy__": base64.b64encode(buf.getvalue()).decode()}


def _decode(obj):
    if isinstance(obj, dict) and "__npy__" in obj:
        return np.load(io.BytesIO(base64.b64decode(obj["__npy__"])),
                       allow_pickle=False)
    return obj


class JsonWriter:
    """Append sample batches to JSON-lines files (reference:
    offline/json_writer.py). One file per writer; rolls at
    max_file_size bytes."""

    def __init__(self, path: str, max_file_size: int = 64 << 20):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.max_file_size = max_file_size
        self._f = None
        self._bytes = 0

    def _open(self):
        name = f"experiences_{int(time.time() * 1000)}_{os.getpid()}.json"
        self._f = open(os.path.join(self.path, name), "a")
        self._bytes = 0

    def write(self, batch: Dict[str, Any]) -> None:
        """batch: column dict (obs/actions/rewards/... -> arrays)."""
        if self._f is None or self._bytes > self.max_file_size:
            if self._f is not None:
                self._f.close()
            self._open()
        line = json.dumps({k: _encode(v) if isinstance(
            v, (np.ndarray, list)) else v for k, v in batch.items()})
        self._f.write(line + "\n")
        self._bytes += len(line) + 1
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JsonReader:
    """Read experience files back as column batches (reference:
    offline/json_reader.py)."""

    def __init__(self, paths):
        if isinstance(paths, str):
            if os.path.isdir(paths):
                paths = sorted(_glob.glob(os.path.join(paths, "*.json")))
            else:
                paths = sorted(_glob.glob(paths)) or [paths]
        self.files: List[str] = list(paths)
        if not self.files:
            raise FileNotFoundError("no experience files found")

    def read_batches(self) -> Iterator[Dict[str, Any]]:
        for f in self.files:
            with open(f) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    raw = json.loads(line)
                    yield {k: _decode(v) for k, v in raw.items()}

    def read_all(self) -> Dict[str, np.ndarray]:
        """All batches concatenated column-wise."""
        cols: Dict[str, list] = {}
        for batch in self.read_batches():
            for k, v in batch.items():
                cols.setdefault(k, []).append(np.asarray(v))
        return {k: np.concatenate(v) for k, v in cols.items()}

    def as_dataset(self, parallelism: int = 8):
        """ray_tpu.data Dataset of per-step rows — feed straight into
        BCConfig/MARWILConfig/CQLConfig.offline_data(dataset=...)."""
        from ray_tpu import data

        cols = self.read_all()
        n = len(next(iter(cols.values()))) if cols else 0
        rows = [{k: v[i] for k, v in cols.items()} for i in range(n)]
        return data.from_items(rows, parallelism=parallelism)


def collect_experiences(algorithm, path: str, steps_per_round: int = 512,
                        num_rounds: int = 1) -> str:
    """Sample the algorithm's env runners and persist the rollouts
    (reference: the `output` config writing rollouts during training).
    Returns the output dir."""
    with JsonWriter(path) as writer:
        for _ in range(num_rounds):
            batch = algorithm.env_runner_group.sample(steps_per_round)
            writer.write(dict(batch))
    return path
