"""RLModule — the neural-network component of an algorithm, in JAX.

Reference: rllib/core/rl_module/rl_module.py (RLModule: forward_
exploration/inference/train over a framework-specific network). TPU-first
difference: modules are pure functions over a params pytree (haiku-style),
so the same module runs vmapped/jitted on the learner (TPU) and eagerly on
CPU env runners from numpy weights — no torch/DDP wrapping.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class RLModuleSpec:
    """Builds an RLModule from config (reference: SingleAgentRLModuleSpec)."""

    module_class: type
    obs_dim: int = 0
    num_actions: int = 0
    model_config: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def build(self) -> "RLModule":
        return self.module_class(self.obs_dim, self.num_actions,
                                 self.model_config)


class RLModule:
    """Pure-functional module: params pytree + forward methods."""

    def init_params(self, rng: jax.Array) -> Any:
        raise NotImplementedError

    def forward_train(self, params: Any, obs: jnp.ndarray) -> Dict[str, Any]:
        """Differentiable path used by the learner loss."""
        raise NotImplementedError

    def forward_exploration(self, params: Any, obs: jnp.ndarray,
                            rng: jax.Array) -> Dict[str, Any]:
        """Stochastic action selection for rollouts."""
        raise NotImplementedError

    def forward_inference(self, params: Any,
                          obs: jnp.ndarray) -> Dict[str, Any]:
        """Greedy action selection for evaluation."""
        raise NotImplementedError


def _mlp_init(rng: jax.Array, sizes: Sequence[int]) -> list:
    params = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for key, fan_in, fan_out in zip(keys, sizes[:-1], sizes[1:]):
        w = jax.random.normal(key, (fan_in, fan_out)) * np.sqrt(2.0 / fan_in)
        params.append({"w": w, "b": jnp.zeros((fan_out,))})
    return params


def _mlp_apply(params: list, x: jnp.ndarray,
               final_activation: bool = False) -> jnp.ndarray:
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or final_activation:
            x = jnp.tanh(x)
    return x


class DiscreteMLPModule(RLModule):
    """MLP torso + policy-logits head + value head for discrete actions.

    The default module for PPO (analog of the reference's default
    PPOTorchRLModule built by the catalog)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 model_config: Optional[dict] = None):
        cfg = model_config or {}
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(cfg.get("fcnet_hiddens", (64, 64)))

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        k_torso, k_pi, k_vf = jax.random.split(rng, 3)
        torso_sizes = (self.obs_dim,) + self.hiddens
        return {
            "torso": _mlp_init(k_torso, torso_sizes),
            "pi": _mlp_init(k_pi, (self.hiddens[-1], self.num_actions)),
            "vf": _mlp_init(k_vf, (self.hiddens[-1], 1)),
        }

    def _torso(self, params, obs):
        return _mlp_apply(params["torso"], obs, final_activation=True)

    def forward_train(self, params, obs):
        feat = self._torso(params, obs)
        logits = _mlp_apply(params["pi"], feat)
        value = _mlp_apply(params["vf"], feat)[..., 0]
        return {"action_dist_inputs": logits, "vf_preds": value}

    def forward_exploration(self, params, obs, rng):
        out = self.forward_train(params, obs)
        logits = out["action_dist_inputs"]
        action = jax.random.categorical(rng, logits, axis=-1)
        logp = jax.nn.log_softmax(logits)
        action_logp = jnp.take_along_axis(
            logp, action[..., None], axis=-1)[..., 0]
        return {"actions": action, "action_logp": action_logp,
                "vf_preds": out["vf_preds"]}

    def forward_inference(self, params, obs):
        out = self.forward_train(params, obs)
        return {"actions": jnp.argmax(out["action_dist_inputs"], axis=-1)}


class QNetModule(RLModule):
    """MLP Q-network for DQN (analog of the reference's DQN RLModule)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 model_config: Optional[dict] = None):
        cfg = model_config or {}
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hiddens = tuple(cfg.get("fcnet_hiddens", (64, 64)))

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        sizes = (self.obs_dim,) + self.hiddens + (self.num_actions,)
        return {"q": _mlp_init(rng, sizes)}

    def forward_train(self, params, obs):
        return {"q_values": _mlp_apply(params["q"], obs)}

    def forward_exploration(self, params, obs, rng):
        # Epsilon handling lives in the env runner (needs the schedule).
        q = self.forward_train(params, obs)["q_values"]
        return {"actions": jnp.argmax(q, axis=-1), "q_values": q}

    def forward_inference(self, params, obs):
        q = self.forward_train(params, obs)["q_values"]
        return {"actions": jnp.argmax(q, axis=-1)}


class SACModule(RLModule):
    """Squashed-Gaussian actor + twin Q critics for continuous control.

    Reference: rllib/algorithms/sac/ (SAC RLModule: policy net emitting
    (mu, log_std), tanh squashing onto the action bounds, two independent
    Q networks over (obs, action)). num_actions is the ACTION DIM here;
    model_config carries action_low/action_high bounds."""

    LOG_STD_MIN = -20.0
    LOG_STD_MAX = 2.0

    def __init__(self, obs_dim: int, num_actions: int,
                 model_config: Optional[dict] = None):
        cfg = model_config or {}
        self.obs_dim = obs_dim
        self.act_dim = num_actions
        self.hiddens = tuple(cfg.get("fcnet_hiddens", (64, 64)))
        low = np.asarray(cfg.get("action_low", -1.0), np.float32)
        high = np.asarray(cfg.get("action_high", 1.0), np.float32)
        self.action_scale = (high - low) / 2.0
        self.action_center = (high + low) / 2.0

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
        pi_sizes = (self.obs_dim,) + self.hiddens + (2 * self.act_dim,)
        q_sizes = (self.obs_dim + self.act_dim,) + self.hiddens + (1,)
        return {
            "pi": _mlp_init(k_pi, pi_sizes),
            "q1": _mlp_init(k_q1, q_sizes),
            "q2": _mlp_init(k_q2, q_sizes),
            # log entropy temperature, auto-tuned by the learner.
            "log_alpha": jnp.zeros(()),
        }

    def pi_dist(self, params, obs):
        out = _mlp_apply(params["pi"], obs)
        mu, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mu, log_std

    def sample_action(self, params, obs, rng):
        """Reparameterized tanh-squashed sample + its log-prob."""
        mu, log_std = self.pi_dist(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(rng, mu.shape)
        pre_tanh = mu + std * eps
        tanh_a = jnp.tanh(pre_tanh)
        # Gaussian logp with tanh change-of-variables correction.
        gauss_logp = (-0.5 * ((eps) ** 2 + 2 * log_std +
                              jnp.log(2 * jnp.pi))).sum(-1)
        correction = jnp.log(1.0 - tanh_a ** 2 + 1e-6).sum(-1)
        logp = gauss_logp - correction
        action = tanh_a * self.action_scale + self.action_center
        return action, logp

    def q_values(self, params, obs, action):
        x = jnp.concatenate([obs, action], axis=-1)
        q1 = _mlp_apply(params["q1"], x)[..., 0]
        q2 = _mlp_apply(params["q2"], x)[..., 0]
        return q1, q2

    def forward_train(self, params, obs):
        mu, log_std = self.pi_dist(params, obs)
        return {"mu": mu, "log_std": log_std}

    def forward_exploration(self, params, obs, rng):
        action, logp = self.sample_action(params, obs, rng)
        return {"actions": action, "action_logp": logp}

    def forward_inference(self, params, obs):
        mu, _ = self.pi_dist(params, obs)
        return {"actions": jnp.tanh(mu) * self.action_scale +
                self.action_center}


class DDPGModule(RLModule):
    """Deterministic tanh actor + twin Q critics (DDPG/TD3).

    Reference: rllib_contrib ddpg/td3 models (deterministic policy
    network, Q networks over (obs, action)). Twin critics are always
    present in the params; DDPG uses q1 only, TD3 takes the min.
    Exploration = Gaussian action noise scaled by `exploration_noise`
    (fraction of the action half-range)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 model_config: Optional[dict] = None):
        cfg = model_config or {}
        self.obs_dim = obs_dim
        self.act_dim = num_actions
        self.hiddens = tuple(cfg.get("fcnet_hiddens", (64, 64)))
        low = np.asarray(cfg.get("action_low", -1.0), np.float32)
        high = np.asarray(cfg.get("action_high", 1.0), np.float32)
        self.action_scale = (high - low) / 2.0
        self.action_center = (high + low) / 2.0
        self.exploration_noise = float(cfg.get("exploration_noise", 0.1))

    def init_params(self, rng: jax.Array) -> Dict[str, Any]:
        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
        pi_sizes = (self.obs_dim,) + self.hiddens + (self.act_dim,)
        q_sizes = (self.obs_dim + self.act_dim,) + self.hiddens + (1,)
        return {
            "pi": _mlp_init(k_pi, pi_sizes),
            "q1": _mlp_init(k_q1, q_sizes),
            "q2": _mlp_init(k_q2, q_sizes),
        }

    def action(self, params, obs):
        """Deterministic policy action, squashed onto the bounds."""
        raw = _mlp_apply(params["pi"], obs)
        return jnp.tanh(raw) * self.action_scale + self.action_center

    def q_values(self, params, obs, action):
        x = jnp.concatenate([obs, action], axis=-1)
        q1 = _mlp_apply(params["q1"], x)[..., 0]
        q2 = _mlp_apply(params["q2"], x)[..., 0]
        return q1, q2

    def forward_train(self, params, obs):
        return {"actions": self.action(params, obs)}

    def forward_exploration(self, params, obs, rng):
        a = self.action(params, obs)
        noise = jax.random.normal(rng, a.shape) * \
            self.exploration_noise * self.action_scale
        low = self.action_center - self.action_scale
        high = self.action_center + self.action_scale
        return {"actions": jnp.clip(a + noise, low, high)}

    def forward_inference(self, params, obs):
        return {"actions": self.action(params, obs)}


def params_to_numpy(params: Any) -> Any:
    """Device → host pytree (for shipping weights to env runners)."""
    return jax.tree_util.tree_map(lambda x: np.asarray(x), params)
