"""LearnerGroup — one local learner or a gang of learner actors.

Reference: rllib/core/learner/learner_group.py:83 (gang-starts learner
actors through Ray Train's BackendExecutor, :57,154). Here the remote
path places learner actors via a placement group and wires them into a
ray_tpu.collective group for the gradient allreduce (the host/DCN analog
of torch DDP; on a TPU slice a single learner with a dp-sharded mesh is
the idiomatic setup instead — num_devices_per_learner).
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.utils.sample_batch import SampleBatch

_created_groups = 0


class LearnerGroup:
    def __init__(self, learner_class: type, module_spec, config: dict):
        self.config = config
        self.num_learners = int(config.get("num_learners", 0))
        self._local = None
        self._actors: List[Any] = []
        self._group_name: Optional[str] = None
        if self.num_learners == 0:
            self._local = learner_class(module_spec, config)
        else:
            from ray_tpu import collective as col

            cls = ray_tpu.remote(learner_class)
            opts = {"num_cpus": config.get("num_cpus_per_learner", 1)}
            if config.get("num_tpus_per_learner"):
                opts["num_tpus"] = config["num_tpus_per_learner"]
            self._actors = [cls.options(**opts).remote(module_spec, config)
                            for _ in range(self.num_learners)]
            self._group_name = f"rllib_learners_{uuid.uuid4().hex[:8]}"
            col.create_collective_group(
                self._actors, self.num_learners,
                list(range(self.num_learners)),
                group_name=self._group_name)
            # All learners start from rank-0's weights (DDP invariant).
            weights = ray_tpu.get(self._actors[0].get_weights.remote())
            ref = ray_tpu.put(weights)
            ray_tpu.get([a.set_weights.remote(ref)
                         for a in self._actors[1:]])

    def update(self, batch: SampleBatch) -> Dict[str, float]:
        """One synchronized SGD step across all learners."""
        if self._local is not None:
            return self._local.update(batch)
        n = len(self._actors)
        shard = max(1, len(batch) // n)
        refs = [
            a.update_ddp.remote(
                batch.slice(i * shard,
                            len(batch) if i == n - 1 else (i + 1) * shard),
                self._group_name)
            for i, a in enumerate(self._actors)
        ]
        all_metrics = ray_tpu.get(refs)
        return {k: float(np.mean([m[k] for m in all_metrics]))
                for k in all_metrics[0]}

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def set_weights(self, params) -> None:
        if self._local is not None:
            self._local.set_weights(params)
            return
        ref = ray_tpu.put(params)
        ray_tpu.get([a.set_weights.remote(ref) for a in self._actors])

    def get_state(self) -> Dict[str, Any]:
        if self._local is not None:
            return self._local.get_state()
        return ray_tpu.get(self._actors[0].get_state.remote())

    def set_state(self, state: Dict[str, Any]) -> None:
        if self._local is not None:
            self._local.set_state(state)
            return
        ref = ray_tpu.put(state)
        ray_tpu.get([a.set_state.remote(ref) for a in self._actors])

    def stop(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
