"""JaxLearner — computes losses and applies updates, jit-compiled.

Reference: rllib/core/learner/learner.py:114 (Learner.update_from_batch
:913, compute_gradients :444) and torch_learner.py:61. TPU-first
difference: instead of DDP-wrapping a stateful net, the learner jits a
pure (params, opt_state, batch) -> (params, opt_state, metrics) step; a
multi-device learner shards the batch over a dp mesh axis and XLA inserts
the gradient all-reduce over ICI.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec


class JaxLearner:
    """Base learner; subclasses implement loss_fn."""

    def __init__(self, module_spec: RLModuleSpec, config: dict):
        import jax
        import optax

        self.config = config
        self.module: RLModule = module_spec.build()
        self._rng = jax.random.PRNGKey(config.get("seed", 0))
        self._rng, init_key = jax.random.split(self._rng)
        self.params = self.module.init_params(init_key)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(config.get("grad_clip", 10.0)),
            optax.adam(config.get("lr", 3e-4)),
        )
        self.opt_state = self.optimizer.init(self.params)
        self._step_fn = None
        self._grad_fn = None
        self._mesh = None
        num_devices = int(config.get("num_devices_per_learner", 1))
        if num_devices > 1:
            from ray_tpu.parallel import create_mesh

            self._mesh = create_mesh(
                {"dp": num_devices}, jax.devices()[:num_devices])

    # ---- subclass hook ----

    def loss_fn(self, params, batch: Dict[str, Any],
                rng) -> Tuple[Any, Dict[str, Any]]:
        """Returns (scalar loss, metrics dict of scalars)."""
        raise NotImplementedError

    # ---- update paths ----

    def _build_step(self):
        import jax

        def step(params, opt_state, batch, rng):
            (loss, metrics), grads = jax.value_and_grad(
                self.loss_fn, has_aux=True)(params, batch, rng)
            updates, opt_state = self.optimizer.update(grads, opt_state,
                                                       params)
            params = jax.tree_util.tree_map(
                lambda p, u: p + u, params, updates)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        return jax.jit(step, donate_argnums=(0, 1))

    def _shard_batch(self, batch: Dict[str, np.ndarray]):
        import jax
        import jax.numpy as jnp

        if self._mesh is None:
            return {k: jnp.asarray(v) for k, v in batch.items()}
        from jax.sharding import NamedSharding, PartitionSpec as P

        n = self._mesh.shape["dp"]
        out = {}
        for k, v in batch.items():
            v = np.asarray(v)
            trim = (len(v) // n) * n  # dp-even leading dim
            out[k] = jax.device_put(
                v[:trim], NamedSharding(self._mesh, P("dp")))
        return out

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One SGD step on the full batch."""
        import jax

        if self._step_fn is None:
            self._step_fn = self._build_step()
        self._rng, key = jax.random.split(self._rng)
        self.params, self.opt_state, metrics = self._step_fn(
            self.params, self.opt_state, self._shard_batch(batch), key)
        # Scalars become floats; vector metrics (e.g. per-sample TD errors
        # for prioritized replay) pass through as numpy.
        return {k: (float(v) if getattr(v, "ndim", 0) == 0 else
                    np.asarray(v))
                for k, v in metrics.items()}

    # ---- distributed-data-parallel via host collectives ----

    def compute_gradients(self, batch: Dict[str, np.ndarray]
                          ) -> Tuple[Any, Dict[str, float]]:
        import jax

        if self._grad_fn is None:
            def grad(params, batch, rng):
                return jax.value_and_grad(self.loss_fn, has_aux=True)(
                    params, batch, rng)

            self._grad_fn = jax.jit(grad)
        self._rng, key = jax.random.split(self._rng)
        (loss, metrics), grads = self._grad_fn(
            self.params, self._shard_batch(batch), key)
        metrics = {k: float(v) for k, v in metrics.items()}
        metrics["total_loss"] = float(loss)
        return grads, metrics

    def apply_gradients(self, grads) -> None:
        import jax

        updates, self.opt_state = self.optimizer.update(
            grads, self.opt_state, self.params)
        self.params = jax.tree_util.tree_map(
            lambda p, u: p + u, self.params, updates)

    def update_ddp(self, batch_shard: Dict[str, np.ndarray],
                   group_name: str) -> Dict[str, float]:
        """Data-parallel update across learner actors: local grads, host
        allreduce (ray_tpu.collective), identical apply on every learner
        (reference semantics: torch_learner DDP, torch_learner.py:347)."""
        import jax
        from jax.flatten_util import ravel_pytree

        from ray_tpu import collective as col

        import contextlib

        # The first step jit-compiles compute_gradients AND
        # apply_gradients (minutes on a contended host). busy_section
        # heartbeats the coordinator so peers waiting in allreduce extend
        # their timeout while this rank is provably alive — no blanket
        # 600s timeout needed. Steady-state steps skip the wrapper (no
        # heartbeat thread / coordinator RPCs once warm); covering the
        # whole first step also protects peers' NEXT allreduce while this
        # rank's apply compile runs.
        warm = getattr(self, "_ddp_warm", False)
        ctx = contextlib.nullcontext() if warm else col.busy_section(
            group_name, reason="first-step jit compile")
        # Cold first step keeps a generous allreduce timeout on top of
        # the handshake: busy_section only covers a peer that has
        # REACHED its first step — a peer still constructing (module
        # build, imports, first trace) under load hasn't heartbeat yet
        # and must not trip the 120 s default. Steady state uses it.
        timeout_s = 120.0 if warm else 600.0
        with ctx:
            grads, metrics = self.compute_gradients(batch_shard)
            flat, unravel = ravel_pytree(grads)
            world = col.get_collective_group_size(group_name)
            mean = col.allreduce(np.asarray(flat), group_name=group_name,
                                 timeout_s=timeout_s)
            mean = mean / world
            self.apply_gradients(unravel(mean))
        self._ddp_warm = True
        return metrics

    # ---- state ----

    def get_weights(self):
        from ray_tpu.rllib.core.rl_module import params_to_numpy

        return params_to_numpy(self.params)

    def set_weights(self, params) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(jnp.asarray, params)
        self.opt_state = self.optimizer.init(self.params)

    def get_state(self) -> Dict[str, Any]:
        import jax

        return {
            "params": jax.tree_util.tree_map(np.asarray, self.params),
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
        }

    def set_state(self, state: Dict[str, Any]) -> None:
        import jax
        import jax.numpy as jnp

        self.params = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self.opt_state = jax.tree_util.tree_map(
            jnp.asarray, state["opt_state"])
        self._step_fn = None
        self._grad_fn = None

    def ping(self) -> bool:
        return True
