"""RLModule / Learner core."""
