"""AlgorithmConfig — fluent builder for algorithm hyperparameters.

Reference: rllib/algorithms/algorithm_config.py (AlgorithmConfig with
.environment()/.env_runners()/.training()/.learners() chained setters,
.build_algo() producing the Algorithm).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional, Type


class AlgorithmConfig:
    algo_class: Optional[type] = None

    def __init__(self):
        # environment
        self.env: Any = None
        self.env_config: Dict[str, Any] = {}
        self.seed: int = 0
        # env runners
        self.num_env_runners: int = 0
        self.num_envs_per_runner: int = 1  # vector-env width per runner
        # ConnectorV2 pipeline FACTORIES (reference: rllib/connectors/):
        # callables returning a ConnectorV2, a list of them, or a
        # ConnectorPipelineV2 — built per runner/learner process.
        self.env_to_module_connector = None   # obs -> module inputs
        self.module_to_env_connector = None   # module outputs -> actions
        self.learner_connector = None         # train batch (pre-GAE)
        self.num_cpus_per_env_runner: int = 1
        self.rollout_fragment_length: int = 200
        # training
        self.lr: float = 3e-4
        self.gamma: float = 0.99
        self.train_batch_size: int = 4000
        self.minibatch_size: int = 128
        self.num_epochs: int = 8
        self.grad_clip: float = 10.0
        self.model: Dict[str, Any] = {}
        # learners
        self.num_learners: int = 0
        self.num_cpus_per_learner: int = 1
        self.num_tpus_per_learner: float = 0
        self.num_devices_per_learner: int = 1
        # evaluation (reference: AlgorithmConfig.evaluation())
        self.evaluation_interval: int = 0       # iterations; 0 = off
        self.evaluation_num_env_runners: int = 0  # 0 = local eval runner
        self.evaluation_duration: int = 5       # episodes per evaluation
        # fault tolerance
        self.restart_failed_env_runners: bool = True

    # ---- chained setters (reference API shape) ----

    def environment(self, env=None, *, env_config: Optional[dict] = None,
                    **kwargs) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        self._apply(kwargs)
        return self

    def env_runners(self, **kwargs) -> "AlgorithmConfig":
        self._apply(kwargs)
        return self

    def evaluation(self, **kwargs) -> "AlgorithmConfig":
        self._apply(kwargs)
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        self._apply(kwargs)
        return self

    def learners(self, **kwargs) -> "AlgorithmConfig":
        self._apply(kwargs)
        return self

    def fault_tolerance(self, **kwargs) -> "AlgorithmConfig":
        self._apply(kwargs)
        return self

    def debugging(self, *, seed: Optional[int] = None,
                  **kwargs) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        self._apply(kwargs)
        return self

    def _apply(self, kwargs: Dict[str, Any]) -> None:
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(
                    f"unknown config key {k!r} for "
                    f"{type(self).__name__}")
            setattr(self, k, v)

    # ---- build ----

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> Dict[str, Any]:
        return {k: v for k, v in vars(self).items()
                if not k.startswith("_")}

    def update_from_dict(self, d: Dict[str, Any]) -> "AlgorithmConfig":
        for k, v in d.items():
            if hasattr(self, k):
                setattr(self, k, v)
        return self

    def build_algo(self):
        if self.algo_class is None:
            raise ValueError("config class does not name an algo_class")
        return self.algo_class(config=self)

    # Back-compat alias (reference has both).
    build = build_algo
