"""AlphaZero — self-play MCTS with a learned policy/value net.

Reference: rllib_contrib alpha_zero (Silver et al. 2017: PUCT tree
search guided by a policy/value network, trained from self-play targets
— visit-count policies pi and game outcomes z — no human data, no
rollout heuristics).

Shape here: the policy/value net is a jitted JAX MLP over the canonical
(current-player) board; MCTS is host-side Python (tree control flow is
data-dependent — the wrong shape for XLA; batched leaf evaluation rides
one jit call); self-play games fill a replay of (state, pi, z) and ONE
jitted step trains cross-entropy(policy, pi) + MSE(value, z). Built-in
TicTacToe is the CI game (reference uses its own toy envs for tests).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.rl_module import _mlp_apply, _mlp_init
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer
from ray_tpu.rllib.utils.sample_batch import SampleBatch
from ray_tpu.tune.trainable import Trainable


class TicTacToe:
    """Two-player zero-sum board game in canonical form: the
    observation always shows +1 for the player TO MOVE. Used by the
    AlphaZero tests; any game exposing this interface plugs in."""

    n_actions = 9
    obs_dim = 9

    def initial_state(self) -> np.ndarray:
        return np.zeros(9, np.float32)

    def legal_actions(self, state: np.ndarray) -> np.ndarray:
        return np.nonzero(state == 0)[0]

    def next_state(self, state: np.ndarray, action: int) -> np.ndarray:
        """Apply the move for the player to move, then flip the canonical
        view so the opponent becomes +1."""
        nxt = state.copy()
        nxt[action] = 1.0
        return -nxt

    def terminal_value(self, state: np.ndarray) -> Optional[float]:
        """From the perspective of the player TO MOVE: -1 if the
        opponent (who just moved) won, 0 draw, None if not terminal."""
        b = state.reshape(3, 3)
        lines = list(b) + list(b.T) + [np.diag(b), np.diag(b[:, ::-1])]
        for line in lines:
            if line.sum() == -3:
                return -1.0  # opponent completed a line
        if (state != 0).all():
            return 0.0
        return None


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.game: Any = TicTacToe
        self.num_simulations: int = 32     # MCTS sims per move
        self.c_puct: float = 1.5
        self.dirichlet_alpha: float = 0.6
        self.dirichlet_eps: float = 0.25
        self.temperature_moves: int = 4    # sample pi early, argmax after
        self.games_per_iteration: int = 8
        self.replay_buffer_capacity: int = 20_000
        self.train_batch_size = 128
        self.updates_per_iteration: int = 8
        self.value_loss_coeff: float = 1.0
        self.lr = 3e-3

    @property
    def algo_class(self):
        return AlphaZero


class _MCTS:
    """PUCT search over canonical states. Node key = state bytes."""

    def __init__(self, game, predict, cfg, rng):
        self.game = game
        self.predict = predict     # state [obs] -> (priors [A], value)
        self.cfg = cfg
        self.rng = rng
        self.P: Dict[bytes, np.ndarray] = {}
        self.N: Dict[bytes, np.ndarray] = {}
        self.W: Dict[bytes, np.ndarray] = {}

    def _apply_root_noise(self, state: np.ndarray, key: bytes) -> None:
        """Fresh Dirichlet noise on the CURRENT root's priors — every
        move, not just on first expansion (with tree reuse across moves
        the root is usually already expanded by the previous search)."""
        legal = self.game.legal_actions(state)
        if not len(legal):
            return
        priors = self.P[key]
        noise = np.zeros(self.game.n_actions, np.float32)
        noise[legal] = self.rng.dirichlet(
            [self.cfg.dirichlet_alpha] * len(legal))
        self.P[key] = (1 - self.cfg.dirichlet_eps) * priors + \
            self.cfg.dirichlet_eps * noise

    def policy(self, state: np.ndarray, add_noise: bool) -> np.ndarray:
        if add_noise and state.tobytes() in self.P:
            self._apply_root_noise(state, state.tobytes())
        for _ in range(self.cfg.num_simulations):
            self._simulate(state.copy(), root=state.tobytes(),
                           add_noise=add_noise)
        n = self.N[state.tobytes()]
        total = n.sum()
        if total == 0:
            legal = self.game.legal_actions(state)
            pi = np.zeros(self.game.n_actions, np.float32)
            pi[legal] = 1.0 / len(legal)
            return pi
        return (n / total).astype(np.float32)

    def _expand(self, state: np.ndarray, key: bytes,
                add_noise: bool) -> float:
        priors, value = self.predict(state)
        legal = self.game.legal_actions(state)
        mask = np.zeros(self.game.n_actions, np.float32)
        mask[legal] = 1.0
        priors = priors * mask
        s = priors.sum()
        priors = priors / s if s > 0 else mask / mask.sum()
        if add_noise and len(legal):
            noise = np.zeros(self.game.n_actions, np.float32)
            noise[legal] = self.rng.dirichlet(
                [self.cfg.dirichlet_alpha] * len(legal))
            priors = (1 - self.cfg.dirichlet_eps) * priors + \
                self.cfg.dirichlet_eps * noise
        self.P[key] = priors
        self.N[key] = np.zeros(self.game.n_actions, np.float32)
        self.W[key] = np.zeros(self.game.n_actions, np.float32)
        return float(value)

    def _simulate(self, state: np.ndarray, root: bytes,
                  add_noise: bool) -> None:
        path: List[Tuple[bytes, int]] = []
        value = None
        while True:
            key = state.tobytes()
            term = self.game.terminal_value(state)
            if term is not None:
                value = term
                break
            if key not in self.P:
                value = self._expand(state, key,
                                     add_noise and key == root)
                break
            p, n, w = self.P[key], self.N[key], self.W[key]
            q = np.where(n > 0, w / np.maximum(n, 1), 0.0)
            u = self.cfg.c_puct * p * np.sqrt(n.sum() + 1) / (1 + n)
            scores = q + u
            legal = self.game.legal_actions(state)
            action = legal[np.argmax(scores[legal])]
            path.append((key, int(action)))
            state = self.game.next_state(state, int(action))
        # Backup: value is from the LEAF player's perspective; each step
        # up the tree flips sides.
        for key, action in reversed(path):
            value = -value
            self.N[key][action] += 1
            self.W[key][action] += value


def _az_forward(params, obs):
    """Policy/value net forward — module-level so self-play workers can
    receive it pickled."""
    import jax.numpy as jnp

    feat = _mlp_apply(params["torso"], obs, final_activation=True)
    logits = _mlp_apply(params["pi"], feat)
    value = jnp.tanh(_mlp_apply(params["v"], feat))[..., 0]
    return logits, value


class _NetPredictor:
    """jit + softmax + transposition cache around a forward fn. Shared
    by the driver and the remote self-play workers so inference
    semantics can't drift between the local and distributed paths."""

    # FIFO eviction bound: TicTacToe never gets near it, but any game
    # exposing the documented interface can plug in, and a long
    # self-play run must not accumulate one entry per distinct state.
    CACHE_MAX = 100_000

    def __init__(self, forward_fn):
        self._forward = forward_fn
        self._fn = None
        self._cache: Dict[bytes, tuple] = {}
        self._params = None

    def set_params(self, params) -> None:
        self._params = params
        self._cache.clear()

    def __call__(self, state: np.ndarray):
        import jax

        key = state.tobytes()
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        if self._fn is None:
            def f(params, obs):
                logits, value = self._forward(params, obs[None])
                return jax.nn.softmax(logits)[0], value[0]

            self._fn = jax.jit(f)
        priors, value = self._fn(self._params, state)
        out = (np.asarray(priors), float(value))
        while len(self._cache) >= self.CACHE_MAX:
            del self._cache[next(iter(self._cache))]
        self._cache[key] = out
        return out


def _play_one_game(game, predict, cfg, rng) -> List[tuple]:
    """One self-play game: MCTS policies as targets, outcome z walked
    back with per-move sign flips. THE self-play rules — used by both
    the driver loop and the remote workers."""
    mcts = _MCTS(game, predict, cfg, rng)
    state = game.initial_state()
    history: List[Tuple[np.ndarray, np.ndarray]] = []
    rows: List[tuple] = []
    move = 0
    while True:
        term = game.terminal_value(state)
        if term is not None:
            z = term
            for obs, pi in reversed(history):
                z = -z
                rows.append((obs, pi, np.float32(z)))
            return rows
        pi = mcts.policy(state, add_noise=True)
        history.append((state.copy(), pi))
        if move < cfg.temperature_moves:
            action = int(rng.choice(len(pi), p=pi))
        else:
            action = int(np.argmax(pi))
        state = game.next_state(state, action)
        move += 1


class AlphaZeroSelfPlayWorker:
    """Remote self-play worker: plays whole games with shipped params
    (own MCTS + jitted net) and returns (obs, pi, z) rows. Games are
    independent, so self-play parallelizes perfectly."""

    def __init__(self, config: dict, worker_index: int):
        cfg = AlphaZeroConfig().update_from_dict(config)
        self.cfg = cfg
        self.game = cfg.game() if isinstance(cfg.game, type) else cfg.game
        self._rng = np.random.default_rng(cfg.seed * 1000 + worker_index)
        self._predictor = _NetPredictor(config["forward_fn"])

    def play(self, params, num_games: int) -> tuple:
        self._predictor.set_params(params)
        all_rows: List[tuple] = []
        for _ in range(num_games):
            all_rows.extend(_play_one_game(
                self.game, self._predictor, self.cfg, self._rng))
        return (np.stack([r[0] for r in all_rows]),
                np.stack([r[1] for r in all_rows]),
                np.stack([r[2] for r in all_rows]), num_games)

    def ping(self) -> bool:
        return True


class AlphaZero(Trainable):
    config_class = AlphaZeroConfig

    def setup(self, config) -> None:
        import jax
        import optax

        self.config = config if isinstance(config, AlphaZeroConfig) \
            else AlphaZeroConfig().update_from_dict(dict(config or {}))
        cfg = self.config
        self.game = cfg.game() if isinstance(cfg.game, type) else cfg.game
        obs_dim, n_actions = self.game.obs_dim, self.game.n_actions
        hidden = tuple(cfg.model.get("fcnet_hiddens", (64, 64))) \
            if cfg.model else (64, 64)

        rng = jax.random.PRNGKey(cfg.seed)
        k_torso, k_pi, k_v = jax.random.split(rng, 3)
        self.params = {
            "torso": _mlp_init(k_torso, (obs_dim,) + hidden),
            "pi": _mlp_init(k_pi, (hidden[-1], n_actions)),
            "v": _mlp_init(k_v, (hidden[-1], 1)),
        }
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr))
        self.opt_state = self.optimizer.init(self.params)
        self._replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                    seed=cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)
        self._step_fn = None
        self._iteration = 0
        self._games_played = 0
        # Shared inference wrapper (jit + softmax + transposition
        # cache); set_params clears the cache on every params change.
        self._predictor = _NetPredictor(_az_forward)
        self._predictor.set_params(self.params)
        # Distributed self-play (num_env_runners > 0): games are
        # independent, so whole games fan out to remote workers that
        # get fresh params each iteration (QMIX-collector pattern).
        self._worker_manager = None
        if cfg.num_env_runners > 0:
            import ray_tpu
            from ray_tpu.rllib.utils.actor_manager import \
                FaultTolerantActorManager

            worker_cfg = dict(cfg.to_dict())
            worker_cfg["forward_fn"] = _az_forward
            cls = ray_tpu.remote(AlphaZeroSelfPlayWorker)

            def factory(i: int):
                return cls.options(
                    num_cpus=cfg.num_cpus_per_env_runner,
                    max_restarts=1).remote(worker_cfg, i + 1)

            self._worker_manager = FaultTolerantActorManager(
                [factory(i) for i in range(cfg.num_env_runners)],
                factory)

    # ---- network ----

    def _forward(self, params, obs):
        return _az_forward(params, obs)

    # ---- self-play ----

    def _self_play_game(self) -> List[tuple]:
        return _play_one_game(self.game, self._predictor, self.config,
                              self._rng)

    # ---- learning ----

    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        logits, value = self._forward(params, batch["obs"])
        logp = jax.nn.log_softmax(logits)
        policy_loss = -(batch["pi"] * logp).sum(-1).mean()
        value_loss = ((value - batch["z"]) ** 2).mean()
        total = policy_loss + \
            self.config.value_loss_coeff * value_loss
        return total, {"policy_loss": policy_loss,
                       "value_loss": value_loss}

    def _update(self, batch) -> Dict[str, float]:
        import jax
        import optax

        if self._step_fn is None:
            def step(params, opt_state, batch):
                (loss, metrics), grads = jax.value_and_grad(
                    self._loss, has_aux=True)(params, batch)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, metrics

            self._step_fn = jax.jit(step)
        self.params, self.opt_state, metrics = self._step_fn(
            self.params, self.opt_state, batch)
        self._predictor.set_params(self.params)
        return {k: float(v) for k, v in metrics.items()}

    # ---- Trainable ----

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        new_rows = 0
        if self._worker_manager is not None:
            new_rows = self._distributed_self_play()
        else:
            for _ in range(cfg.games_per_iteration):
                rows = self._self_play_game()
                self._games_played += 1
                new_rows += len(rows)
                self._replay.add(SampleBatch({
                    "obs": np.stack([r[0] for r in rows]),
                    "pi": np.stack([r[1] for r in rows]),
                    "z": np.stack([r[2] for r in rows]),
                }))
        metrics: Dict[str, Any] = {
            "games_played": self._games_played,
            "replay_size": len(self._replay),
            "new_rows": new_rows,
        }
        if self._worker_manager is not None:
            metrics["num_self_play_workers"] = \
                self._worker_manager.num_healthy_actors()
        if len(self._replay) >= cfg.train_batch_size:
            for _ in range(cfg.updates_per_iteration):
                batch = dict(self._replay.sample(cfg.train_batch_size))
                metrics.update(self._update(batch))
        self._iteration += 1
        metrics["training_iteration"] = self._iteration
        return metrics

    def _distributed_self_play(self) -> int:
        import jax

        import ray_tpu

        cfg = self.config
        mgr = self._worker_manager
        mgr.probe_unhealthy()
        ids = mgr.healthy_actor_ids()
        if not ids:
            raise RuntimeError("all self-play workers are dead")
        total, n = cfg.games_per_iteration, len(ids)
        shards = {wid: total // n + (1 if k < total % n else 0)
                  for k, wid in enumerate(ids)}
        params_ref = ray_tpu.put(
            jax.tree_util.tree_map(np.asarray, self.params))
        results = mgr.foreach_sharded(
            lambda a, games: a.play.remote(params_ref, games),
            {wid: g for wid, g in shards.items() if g > 0})
        new_rows = 0
        for _, (obs, pi, z, games) in results.ok:
            self._replay.add(SampleBatch(
                {"obs": obs, "pi": pi, "z": z}))
            new_rows += len(z)
            self._games_played += games
        return new_rows

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        import os
        import pickle

        import jax

        with open(os.path.join(checkpoint_dir, "az_state.pkl"),
                  "wb") as f:
            pickle.dump({
                "params": jax.tree_util.tree_map(
                    np.asarray, self.params),
                "opt_state": jax.tree_util.tree_map(
                    np.asarray, self.opt_state),
                "games_played": self._games_played,
                "iteration": self._iteration,
            }, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        import jax
        import jax.numpy as jnp

        with open(os.path.join(checkpoint_dir, "az_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        self.params = jax.tree_util.tree_map(jnp.asarray,
                                             state["params"])
        self.opt_state = jax.tree_util.tree_map(jnp.asarray,
                                                state["opt_state"])
        self._games_played = state["games_played"]
        self._iteration = state["iteration"]
        self._step_fn = None
        # Restored params invalidate any cached net outputs.
        self._predictor.set_params(self.params)

    def cleanup(self) -> None:
        if self._worker_manager is not None:
            self._worker_manager.shutdown()
            self._worker_manager = None

    stop = cleanup

    # ---- evaluation ----

    def play_vs_random(self, num_games: int = 20,
                       simulations: Optional[int] = None
                       ) -> Dict[str, float]:
        """Agent (MCTS, no noise) vs a uniform-random opponent,
        alternating who moves first. Returns win/draw/loss rates from
        the agent's perspective."""
        cfg = self.config
        sims = simulations if simulations is not None \
            else cfg.num_simulations
        wins = draws = losses = 0
        rng = np.random.default_rng(123)
        for g in range(num_games):
            mcts = _MCTS(self.game, self._predictor, cfg, rng)
            state = self.game.initial_state()
            agent_to_move = (g % 2 == 0)
            while True:
                term = self.game.terminal_value(state)
                if term is not None:
                    # term: to-move player's result. agent_to_move says
                    # whose perspective that is.
                    if term == 0:
                        draws += 1
                    elif (term < 0) == agent_to_move:
                        losses += 1
                    else:
                        wins += 1
                    break
                legal = self.game.legal_actions(state)
                if agent_to_move:
                    for _ in range(sims):
                        mcts._simulate(state.copy(),
                                       root=state.tobytes(),
                                       add_noise=False)
                    n = mcts.N[state.tobytes()]
                    action = legal[np.argmax(n[legal])]
                else:
                    action = rng.choice(legal)
                state = self.game.next_state(state, int(action))
                agent_to_move = not agent_to_move
        return {"win_rate": wins / num_games,
                "draw_rate": draws / num_games,
                "loss_rate": losses / num_games}
