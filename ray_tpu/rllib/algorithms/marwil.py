"""MARWIL — monotonic advantage re-weighted imitation learning.

Reference: rllib/algorithms/marwil/ (offline RL: behavior cloning
weighted by exp(beta * advantage), with a jointly-trained value head
providing the advantages; beta=0 degenerates to BC). The offline dataset
carries (obs, actions, rewards [, eps_id/terminateds]); discounted
returns-to-go are computed at setup and the loss re-weights the
log-likelihood by the centered advantage exponent.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule
from ray_tpu.rllib.utils import sample_batch as sb


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.offline_dataset: Any = None
        self.beta: float = 1.0  # 0 => plain BC
        self.vf_coeff: float = 1.0
        self.max_advantage_weight: float = 20.0
        self.train_batch_size = 256
        self.num_env_runners = 0

    def offline_data(self, *, dataset=None, **kwargs) -> "MARWILConfig":
        if dataset is not None:
            self.offline_dataset = dataset
        self._apply(kwargs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        d = super().to_dict()
        d.pop("offline_dataset", None)  # stays driver-side
        return d

    @property
    def algo_class(self):
        return MARWIL


class MARWILLearner(JaxLearner):
    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        beta = cfg.get("beta", 1.0)
        out = self.module.forward_train(params, batch[sb.OBS])
        logits = out["action_dist_inputs"]
        values = out["vf_preds"]
        returns = batch["returns"]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[sb.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None],
                                   axis=-1)[:, 0]

        adv = jax.lax.stop_gradient(returns - values)
        # Moving-free normalization: scale by the batch RMS (reference
        # keeps a running average; batch RMS is the stationary analog).
        adv_rms = jnp.sqrt(jnp.mean(adv ** 2) + 1e-8)
        weights = jnp.exp(jnp.clip(beta * adv / adv_rms, -10.0, 10.0))
        weights = jnp.minimum(weights,
                              cfg.get("max_advantage_weight", 20.0))
        policy_loss = -(weights * logp).mean()
        vf_loss = ((values - returns) ** 2).mean()
        total = policy_loss + cfg.get("vf_coeff", 1.0) * vf_loss
        accuracy = (jnp.argmax(logits, -1) == actions).mean()
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "mean_weight": weights.mean(),
                       "accuracy": accuracy}


def _returns_to_go(rewards: np.ndarray, dones: np.ndarray,
                   gamma: float) -> np.ndarray:
    out = np.zeros_like(rewards, np.float32)
    acc = 0.0
    for t in range(len(rewards) - 1, -1, -1):
        if dones[t]:
            acc = 0.0
        acc = rewards[t] + gamma * acc
        out[t] = acc
    return out


class MARWIL(Algorithm):
    config_class = MARWILConfig
    learner_class = MARWILLearner
    module_class = DiscreteMLPModule

    def setup(self, config) -> None:
        super().setup(config)
        ds = self.config.offline_dataset
        if ds is None:
            raise ValueError(
                "MARWILConfig.offline_data(dataset=...) required")
        if hasattr(ds, "take_all"):  # ray_tpu.data Dataset
            rows = ds.take_all()
            ds = {k: np.asarray([r[k] for r in rows])
                  for k in rows[0]}
        self._obs = np.asarray(ds["obs"], np.float32)
        self._actions = np.asarray(ds["actions"])
        rewards = np.asarray(ds.get("rewards",
                                    np.zeros(len(self._obs))), np.float32)
        dones = np.array(
            ds.get("terminateds", ds.get("dones",
                                         np.zeros(len(self._obs)))),
            dtype=bool)  # copy: we write dones[-1] below
        dones[-1] = True  # the log ends here regardless
        returns = _returns_to_go(rewards, dones, self.config.gamma)
        # Standardize: raw returns (hundreds for long episodes) through
        # the SHARED torso would make the value loss drown the policy
        # gradient; advantages are scale-free after the loss's RMS
        # normalization, so a monotonic affine transform is safe.
        self._returns = ((returns - returns.mean()) /
                         (returns.std() + 1e-8)).astype(np.float32)
        self._rng = np.random.default_rng(self.config.seed)

    def training_step(self) -> Dict[str, Any]:
        from ray_tpu.rllib.utils.sample_batch import SampleBatch

        idx = self._rng.integers(0, len(self._obs),
                                 self.config.train_batch_size)
        batch = SampleBatch({
            sb.OBS: self._obs[idx],
            sb.ACTIONS: self._actions[idx],
            "returns": self._returns[idx],
        })
        return self.learner_group.update(batch)
