"""CQL — conservative Q-learning for offline continuous control.

Reference: rllib/algorithms/cql/ (Kumar et al. 2020 on top of SAC: the
critic loss adds a conservative regularizer
alpha_prime * (logsumexp_a Q(s, a) - Q(s, a_data)) that pushes down
Q-values of out-of-distribution actions, so the learned policy cannot
exploit extrapolation error in the fixed dataset). The logsumexp is
estimated from uniform + current-policy action samples, all inside the
one jit-compiled SAC update step.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.sac import SACConfig, SACLearner
from ray_tpu.rllib.core.rl_module import SACModule
from ray_tpu.rllib.utils import sample_batch as sb


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.offline_dataset: Any = None
        self.cql_alpha: float = 1.0       # conservative penalty weight
        self.cql_n_actions: int = 4       # samples for the logsumexp
        self.num_env_runners = 0
        self.updates_per_step = 8

    def offline_data(self, *, dataset=None, **kwargs) -> "CQLConfig":
        if dataset is not None:
            self.offline_dataset = dataset
        self._apply(kwargs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        d = super().to_dict()
        d.pop("offline_dataset", None)
        return d

    @property
    def algo_class(self):
        return CQL


class CQLLearner(SACLearner):
    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        module = self.module
        sac_loss, metrics = super().loss_fn(params, batch, rng)

        # --- conservative penalty on both critics ---
        obs = batch[sb.OBS]
        actions = batch[sb.ACTIONS]
        if actions.ndim == 1:
            actions = actions[:, None]
        n = cfg.get("cql_n_actions", 4)
        b = obs.shape[0]
        act_dim = module.act_dim
        rng_u, rng_pi = jax.random.split(jax.random.fold_in(rng, 7))
        lo = module.action_center - module.action_scale
        hi = module.action_center + module.action_scale
        rand_a = jax.random.uniform(rng_u, (n, b, act_dim),
                                    minval=lo, maxval=hi)
        pi_keys = jax.random.split(rng_pi, n)
        # Detach: the penalty regularizes the CRITICS; without the stop,
        # minimizing logsumexp Q(s, a_pi) would train the actor to pick
        # low-Q actions, fighting the SAC actor loss.
        pi_a = jax.lax.stop_gradient(
            jnp.stack([module.sample_action(params, obs, k)[0]
                       for k in pi_keys]))
        all_a = jnp.concatenate([rand_a, pi_a])       # [2n, B, A]
        obs_rep = jnp.broadcast_to(obs, (2 * n,) + obs.shape)
        q1_all, q2_all = module.q_values(
            params, obs_rep.reshape(2 * n * b, -1),
            all_a.reshape(2 * n * b, act_dim))
        q1_all = q1_all.reshape(2 * n, b)
        q2_all = q2_all.reshape(2 * n, b)
        q1_data, q2_data = module.q_values(params, obs, actions)
        gap1 = jax.scipy.special.logsumexp(q1_all, axis=0) - q1_data
        gap2 = jax.scipy.special.logsumexp(q2_all, axis=0) - q2_data
        cql_penalty = (gap1.mean() + gap2.mean())
        alpha_prime = cfg.get("cql_alpha", 1.0)
        total = sac_loss + alpha_prime * cql_penalty
        metrics = dict(metrics)
        metrics["cql_penalty"] = cql_penalty
        metrics["conservative_gap"] = gap1.mean()
        return total, metrics


class CQL(Algorithm):
    config_class = CQLConfig
    learner_class = CQLLearner
    module_class = SACModule

    def setup(self, config) -> None:
        cfg = config if isinstance(config, CQLConfig) else \
            self.config_class().update_from_dict(dict(config or {}))
        if cfg.num_learners != 0:
            raise ValueError("CQL uses a local learner")
        super().setup(cfg)
        ds = self.config.offline_dataset
        if ds is None:
            raise ValueError("CQLConfig.offline_data(dataset=...) required")
        self._data = {
            sb.OBS: np.asarray(ds["obs"], np.float32),
            sb.ACTIONS: np.asarray(ds["actions"], np.float32),
            sb.REWARDS: np.asarray(ds["rewards"], np.float32),
            sb.NEXT_OBS: np.asarray(ds["next_obs"], np.float32),
            sb.TERMINATEDS: np.asarray(ds["terminateds"], bool),
        }
        self._rng = np.random.default_rng(self.config.seed)

    @property
    def _learner(self) -> CQLLearner:
        return self.learner_group._local

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        n = len(self._data[sb.OBS])
        metrics: Dict[str, Any] = {}
        for _ in range(cfg.updates_per_step):
            idx = self._rng.integers(0, n, cfg.train_batch_size)
            batch = {k: v[idx] for k, v in self._data.items()}
            m = self._learner.update_sac(batch)
            self._learner.sync_target(cfg.tau)
            metrics.update(m)
        return metrics
