"""Multi-agent PPO — independent PPO learners over a shared env.

Reference: rllib's multi-agent stack (rllib/env/multi_agent_env_runner.py:54
+ MultiRLModule in rllib/core/rl_module/multi_rl_module.py): N agents map
to M policy modules via policy_mapping_fn; each module trains on its own
experience (independent PPO — the reference's default when policies
don't share weights).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.ppo import PPOLearner
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule, RLModuleSpec
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.env.multi_agent_env_runner import MultiAgentEnvRunner
from ray_tpu.rllib.env.registry import make_env
from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.postprocessing import compute_gae, standardize
from ray_tpu.tune.trainable import Trainable


class MultiAgentPPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lambda_: float = 0.95
        self.clip_param: float = 0.2
        self.vf_clip_param: float = 10.0
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.0
        # agent_id -> module_id; default: one module per agent.
        self.policy_mapping_fn: Optional[Callable[[str], str]] = None

    def multi_agent(self, *, policy_mapping_fn=None
                    ) -> "MultiAgentPPOConfig":
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    @property
    def algo_class(self):
        return MultiAgentPPO


class MultiAgentPPO(Trainable):
    """Independent-PPO trainer over a MultiAgentEnv."""

    config_class = MultiAgentPPOConfig

    def setup(self, config) -> None:
        if isinstance(config, MultiAgentPPOConfig):
            self.config = config
        else:
            self.config = self.config_class().update_from_dict(
                dict(config or {}))
        cfg = self.config
        probe = make_env(cfg.env, cfg.env_config)
        mapping = cfg.policy_mapping_fn or (lambda aid: aid)
        self._mapping = mapping

        # One module spec per distinct module id, sized by (any of) its
        # agents' spaces.
        self.module_specs: Dict[str, RLModuleSpec] = {}
        for aid in probe.agent_ids:
            mid = mapping(aid)
            if mid in self.module_specs:
                continue
            obs_dim = int(probe.observation_space_of(aid).shape[0])
            num_actions = int(probe.action_space_of(aid).n)
            self.module_specs[mid] = RLModuleSpec(
                DiscreteMLPModule, obs_dim, num_actions, dict(cfg.model))

        run_cfg = cfg.to_dict()
        run_cfg["module_specs"] = self.module_specs
        run_cfg["policy_mapping_fn"] = mapping
        self.learners: Dict[str, PPOLearner] = {
            mid: PPOLearner(spec, run_cfg)
            for mid, spec in self.module_specs.items()}
        # Runner management (incl. fault tolerance) reuses EnvRunnerGroup
        # with the multi-agent runner class.
        self.env_runner_group = EnvRunnerGroup(
            run_cfg, runner_cls=MultiAgentEnvRunner)
        self._sync_weights()
        self._iteration = 0

    def _get_weights(self) -> Dict[str, Any]:
        return {mid: learner.get_weights()
                for mid, learner in self.learners.items()}

    def _sync_weights(self) -> None:
        self.env_runner_group.sync_weights(self._get_weights())

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        from ray_tpu.rllib.utils.sample_batch import SampleBatch

        per_module: Dict[str, List] = {}
        for batches, boots in self.env_runner_group.sample_multi(
                cfg.train_batch_size):
            for mid, per_agent in batches.items():
                for aid, batch in per_agent.items():
                    gae = compute_gae(batch, cfg.gamma, cfg.lambda_,
                                      boots.get(aid, 0.0))
                    per_module.setdefault(mid, []).append(gae)

        metrics: Dict[str, Any] = {}
        rng = np.random.default_rng(cfg.seed + self._iteration)
        for mid, parts in per_module.items():
            train_batch = SampleBatch.concat_samples(parts)
            train_batch[sb.ADVANTAGES] = standardize(
                train_batch[sb.ADVANTAGES])
            m: Dict[str, Any] = {}
            for _ in range(cfg.num_epochs):
                for minibatch in train_batch.minibatches(
                        min(cfg.minibatch_size, len(train_batch)), rng):
                    m = self.learners[mid].update(minibatch)
            metrics[mid] = m
            metrics[f"{mid}/steps_trained"] = len(train_batch)
        self._sync_weights()
        self._iteration += 1
        if cfg.restart_failed_env_runners:
            restored = self.env_runner_group.restore_failed(
                self._get_weights)
            if restored:
                metrics["num_env_runners_restored"] = restored
        metrics.update(self.env_runner_group.aggregate_metrics())
        metrics["training_iteration"] = self._iteration
        return metrics

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        import os
        import pickle

        state = {
            "learners": {mid: lr.get_state()
                         for mid, lr in self.learners.items()},
            "iteration": self._iteration,
        }
        with open(os.path.join(checkpoint_dir, "ma_state.pkl"),
                  "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir, "ma_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        learners = state.get("learners", state)  # fwd-compat
        for mid, s in learners.items():
            self.learners[mid].set_state(s)
        self._iteration = state.get("iteration", 0)
        self._sync_weights()

    def cleanup(self) -> None:
        self.env_runner_group.stop()

    stop = cleanup
