"""DDPG / TD3 — deterministic-policy continuous control.

Reference: rllib_contrib ddpg (Deep Deterministic Policy Gradient:
deterministic actor, Q critic, polyak targets, Gaussian exploration
noise) and td3 (TD3 = DDPG + clipped double-Q, target policy smoothing,
delayed policy updates — Fujimoto et al. 2018).

Architecture mirrors SAC here: the whole update is ONE jit-compiled JAX
step; target params thread through the batch so the step stays pure and
polyak sync happens outside the jit. TD3's policy delay is implemented
by an `update_actor` flag multiplied into the actor loss term: on
critic-only steps the actor's gradient contribution is exactly zero
(the shared Adam state still ticks, a documented deviation from
separate per-network optimizers).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.off_policy import OffPolicyAlgorithm
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import DDPGModule
from ray_tpu.rllib.utils import sample_batch as sb


class DDPGConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.replay_buffer_capacity: int = 100_000
        self.num_steps_sampled_before_learning_starts: int = 1_000
        self.tau: float = 0.005
        self.exploration_noise: float = 0.1   # of the action half-range
        self.twin_q: bool = False
        self.target_noise: float = 0.0        # TD3 smoothing (off)
        self.target_noise_clip: float = 0.5
        self.policy_delay: int = 1
        self.rollout_fragment_length = 64
        self.train_batch_size = 256
        self.updates_per_step: int = 16
        self.lr = 3e-3

    @property
    def algo_class(self):
        return DDPG


class TD3Config(DDPGConfig):
    def __init__(self):
        super().__init__()
        self.twin_q = True
        self.target_noise = 0.2
        self.policy_delay = 2

    @property
    def algo_class(self):
        return TD3


class DDPGLearner(JaxLearner):
    def __init__(self, module_spec, config):
        super().__init__(module_spec, config)
        import jax
        import jax.numpy as jnp

        self.target_params = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), self.params)
        self._update_count = 0

    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        module = self.module
        gamma = cfg.get("gamma", 0.99)
        twin_q = cfg.get("twin_q", False)
        target_noise = cfg.get("target_noise", 0.0)
        noise_clip = cfg.get("target_noise_clip", 0.5)

        obs = batch[sb.OBS]
        next_obs = batch[sb.NEXT_OBS]
        actions = batch[sb.ACTIONS]
        if actions.ndim == 1:
            actions = actions[:, None]
        target = batch["target_params"]

        # --- critic target: y = r + gamma (1-d) Q_t(s', mu_t(s') + eps) ---
        next_a = module.action(target, next_obs)
        if target_noise > 0.0:
            eps = jnp.clip(
                jax.random.normal(rng, next_a.shape) * target_noise *
                module.action_scale,
                -noise_clip * module.action_scale,
                noise_clip * module.action_scale)
            low = module.action_center - module.action_scale
            high = module.action_center + module.action_scale
            next_a = jnp.clip(next_a + eps, low, high)
        tq1, tq2 = module.q_values(target, next_obs, next_a)
        tq = jnp.minimum(tq1, tq2) if twin_q else tq1
        not_done = 1.0 - batch[sb.TERMINATEDS].astype(jnp.float32)
        y = jax.lax.stop_gradient(
            batch[sb.REWARDS] + gamma * not_done * tq)

        q1, q2 = module.q_values(params, obs, actions)
        critic_loss = ((q1 - y) ** 2).mean()
        if twin_q:
            critic_loss = critic_loss + ((q2 - y) ** 2).mean()

        # --- actor: maximize Q1(s, mu(s)) with critics frozen ---
        frozen = jax.lax.stop_gradient(
            {"q1": params["q1"], "q2": params["q2"]})
        pi_a = module.action(params, obs)
        pq1, _ = module.q_values({**params, **frozen}, obs, pi_a)
        actor_loss = -pq1.mean() * batch["update_actor"]

        total = critic_loss + actor_loss
        return total, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "q1_mean": q1.mean(),
            "td_target_mean": y.mean(),
        }

    def update_ddpg(self, batch: Dict[str, np.ndarray]
                    ) -> Dict[str, float]:
        self._update_count += 1
        delay = int(self.config.get("policy_delay", 1))
        batch = dict(batch)
        batch["target_params"] = self.target_params
        batch["update_actor"] = np.float32(
            1.0 if self._update_count % delay == 0 else 0.0)
        return self.update(batch)

    def _shard_batch(self, batch):
        batch = dict(batch)
        target = batch.pop("target_params", None)
        flag = batch.pop("update_actor", None)
        out = super()._shard_batch(batch)
        if target is not None:
            out["target_params"] = target
        if flag is not None:
            out["update_actor"] = flag
        return out

    def sync_target(self, tau: float) -> None:
        import jax

        self.target_params = jax.tree_util.tree_map(
            lambda t, p: t * (1 - tau) + p * tau,
            self.target_params, self.params)

    def get_state(self):
        import jax

        state = super().get_state()
        state["target_params"] = jax.tree_util.tree_map(
            np.asarray, self.target_params)
        state["update_count"] = self._update_count
        return state

    def set_state(self, state) -> None:
        import jax
        import jax.numpy as jnp

        super().set_state(state)
        if "target_params" in state:
            self.target_params = jax.tree_util.tree_map(
                jnp.asarray, state["target_params"])
        else:
            self.target_params = jax.tree_util.tree_map(
                lambda x: jnp.array(x, copy=True), self.params)
        self._update_count = state.get("update_count", 0)


class DDPG(OffPolicyAlgorithm):
    config_class = DDPGConfig
    learner_class = DDPGLearner
    module_class = DDPGModule

    def setup(self, config) -> None:
        cfg = config if isinstance(config, self.config_class) else \
            self.config_class().update_from_dict(dict(config or {}))
        # The runner's exploration noise comes from the module config.
        model = dict(cfg.model)
        model.setdefault("exploration_noise", cfg.exploration_noise)
        cfg.model = model
        super().setup(cfg)

    def _update_once(self, batch) -> Dict[str, float]:
        return self._learner.update_ddpg(batch)


class TD3(DDPG):
    config_class = TD3Config
