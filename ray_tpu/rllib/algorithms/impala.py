"""IMPALA — importance-weighted actor-learner with V-trace.

Reference: rllib/algorithms/impala/ (V-trace off-policy correction,
Espeholt et al. 2018). The actor-learner decoupling shows up here as
behavior-policy log-probs recorded at sample time: by the time the
learner consumes a rollout the weights have moved, and V-trace's
clipped importance ratios (rho/c) correct the value targets. The loss
is jit-compiled JAX; V-trace targets are computed inside the loss from
the learner's own value predictions (single fused XLA program rather
than a separate host pass).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule
from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.vtrace_clip_rho_threshold: float = 1.0
        self.vtrace_clip_c_threshold: float = 1.0
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.train_batch_size = 512
        self.num_epochs = 1  # IMPALA is single-pass over each rollout
        self.minibatch_size = 512

    @property
    def algo_class(self):
        return IMPALA


class IMPALALearner(JaxLearner):
    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        out = self.module.forward_train(params, batch[sb.OBS])
        logits = out["action_dist_inputs"]
        values = out["vf_preds"]                       # [T]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[sb.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None],
                                   axis=-1)[:, 0]
        behavior_logp = batch[sb.ACTION_LOGP]
        rewards = batch[sb.REWARDS]
        boundary = batch["boundary"].astype(jnp.float32)  # next is new ep
        # Host-computed bootstrap at every seam (terminal -> 0, rollout
        # tail -> the runner's exact bootstrap, truncation/cut -> stale
        # behavior value); NaN-free override mask.
        next_value_override = batch["next_value_override"]
        gamma = cfg.get("gamma", 0.99)

        rho = jnp.exp(logp - behavior_logp)
        rho_bar = jnp.minimum(
            rho, cfg.get("vtrace_clip_rho_threshold", 1.0))
        c_bar = jnp.minimum(rho, cfg.get("vtrace_clip_c_threshold", 1.0))

        values_next = jnp.concatenate(
            [values[1:], jnp.zeros((1,), values.dtype)])
        # At seams the learner's values[t+1] belongs to a different
        # episode/shard — use the host-provided bootstrap instead.
        values_next = jnp.where(boundary > 0, next_value_override,
                                values_next)
        not_done = 1.0 - boundary  # scan must not leak across seams
        deltas = rho_bar * (rewards + gamma * values_next - values)

        # Backward scan: vs - V(s) accumulation.
        def scan_fn(carry, xs):
            delta, c, nd = xs
            acc = delta + gamma * c * nd * carry
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            scan_fn, jnp.zeros((), values.dtype),
            (deltas, c_bar, not_done), reverse=True)
        vs = jax.lax.stop_gradient(vs_minus_v + values)
        vs_next = jnp.concatenate([vs[1:], jnp.zeros((1,), vs.dtype)])
        vs_next = jnp.where(boundary > 0, next_value_override, vs_next)

        pg_adv = jax.lax.stop_gradient(
            rho_bar * (rewards + gamma * vs_next - values))
        policy_loss = -(logp * pg_adv).mean()
        vf_loss = ((values - vs) ** 2).mean()
        probs = jax.nn.softmax(logits)
        entropy = -(probs * logp_all).sum(-1).mean()
        total = (policy_loss + cfg.get("vf_loss_coeff", 0.5) * vf_loss -
                 cfg.get("entropy_coeff", 0.01) * entropy)
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "entropy": entropy,
                       "mean_rho": rho.mean()}


class IMPALA(Algorithm):
    config_class = IMPALAConfig
    learner_class = IMPALALearner
    module_class = DiscreteMLPModule

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        pairs = self.env_runner_group.sample_with_bootstraps(
            cfg.train_batch_size)
        batches = []
        for batch, boot in pairs:
            b = SampleBatch(batch)
            eps = np.asarray(b[sb.EPS_ID])
            terms = np.asarray(b[sb.TERMINATEDS], bool)
            vf = np.asarray(b.get(sb.VF_PREDS,
                                  np.zeros(len(b))), np.float32)
            # Seams where V-trace must not use the learner's values[t+1]:
            # episode change mid-rollout or the rollout tail. Bootstrap:
            # terminal -> 0; tail -> the runner's exact bootstrap value;
            # truncation/cut -> the row's own (stale) behavior value.
            boundary = np.zeros(len(b), np.float32)
            boundary[:-1] = (eps[1:] != eps[:-1]).astype(np.float32)
            boundary[-1] = 1.0
            override = np.where(terms, 0.0, vf).astype(np.float32)
            if isinstance(boot, dict):
                # Vector runners: exact per-env bootstraps keyed by the
                # final eps_id of each env's segment.
                for t in np.nonzero(boundary)[0]:
                    e = int(eps[t])
                    if not terms[t] and e in boot:
                        override[t] = boot[e]
            else:
                override[-1] = 0.0 if terms[-1] else float(boot)
            b["boundary"] = boundary
            b["next_value_override"] = override
            batches.append(b)
        train_batch = SampleBatch.concat_samples(batches)
        if cfg.num_learners > 0:
            # DDP learners slice the batch contiguously: cut the V-trace
            # scan at shard edges too (stale-value bootstrap there).
            n = cfg.num_learners
            shard = max(1, len(train_batch) // n)
            boundary = np.asarray(train_batch["boundary"])
            override = np.asarray(train_batch["next_value_override"])
            vf = np.asarray(train_batch.get(
                sb.VF_PREDS, np.zeros(len(train_batch))), np.float32)
            for i in range(1, n):
                edge = i * shard - 1
                if 0 <= edge < len(train_batch) and not boundary[edge]:
                    boundary[edge] = 1.0
                    override[edge] = vf[edge]
            train_batch["boundary"] = boundary
            train_batch["next_value_override"] = override
        metrics = self.learner_group.update(train_batch)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return metrics
