"""Apex-DQN — distributed prioritized experience replay.

Reference: the Ape-X architecture (Horgan et al., ICLR 2018) as shipped
in rllib_contrib/apex_dqn (ApexDQN over
rllib/utils/replay_buffers/): decoupled actors — many env runners feed
SHARDED prioritized replay buffer actors; a central learner samples
round-robin across shards and pushes TD priorities back to the owning
shard. This is the algorithm that exercises the actor runtime itself
(replay shards are plain actors under the FaultTolerantActorManager):
a killed shard is detected on its next RPC, replaced from the factory
(empty), and training continues on the surviving experience.

Simplifications vs the paper, recorded: exploration uses the shared
DQN epsilon schedule rather than per-runner epsilon ladders, and the
learner is the central local learner (target-net state is
per-learner, matching DQN here).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.utils.actor_manager import FaultTolerantActorManager
from ray_tpu.rllib.utils.replay_buffers import PrioritizedReplayBuffer
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class ReplayShardActor:
    """One shard of the distributed prioritized replay buffer."""

    def __init__(self, capacity: int, alpha: float, beta: float,
                 seed: int):
        self.buffer = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                              beta=beta, seed=seed)

    def add(self, cols: Dict[str, np.ndarray]) -> int:
        self.buffer.add(SampleBatch(cols))
        return len(self.buffer)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        batch = self.buffer.sample(batch_size)
        return dict(batch.items())

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> bool:
        self.buffer.update_priorities(np.asarray(idx),
                                      np.asarray(td_errors))
        return True

    def size(self) -> int:
        return len(self.buffer)

    def ping(self) -> str:
        return "pong"  # FaultTolerantActorManager health probe


class ApexDQNConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.prioritized_replay = True  # Ape-X is PER by definition
        self.num_replay_shards: int = 2
        self.replay_shard_capacity: int = 25_000
        self.per_alpha: float = 0.6
        self.per_beta: float = 0.4
        # Distributed sampling is the point: default to remote runners.
        self.num_env_runners = 2

    @property
    def algo_class(self):
        return ApexDQN


class ApexDQN(DQN):
    config_class = ApexDQNConfig

    def setup(self, config) -> None:
        super().setup(config)
        cfg = self.config
        # The local single-process buffer DQN.setup built is unused —
        # replace it with the shard fleet.
        self.replay = None
        remote_cls = ray_tpu.remote(ReplayShardActor)

        def factory(i: int):
            return remote_cls.options(max_restarts=0).remote(
                cfg.replay_shard_capacity, cfg.per_alpha, cfg.per_beta,
                (cfg.seed or 0) + i)

        shards = [factory(i) for i in range(cfg.num_replay_shards)]
        self.replay_shards = FaultTolerantActorManager(shards, factory)
        self._next_shard = 0  # round-robin cursor (adds and samples)
        self._pending_adds: List[Any] = []

    # DQN's replay-dependent state helpers don't apply to shard actors;
    # checkpoint/restore carries the learner + counters only (replay is
    # reconstructible experience, the reference drops it too).
    def get_extra_state(self) -> Dict[str, Any]:
        return {"env_steps": self._env_steps,
                "last_target_sync": self._last_target_sync}

    def set_extra_state(self, state: Dict[str, Any]) -> None:
        if not state:
            return
        self._env_steps = state["env_steps"]
        self._last_target_sync = state["last_target_sync"]

    # ----------------------------------------------------------- internals
    def _rr_shard_ids(self) -> List[int]:
        """Healthy shard ids starting at the round-robin cursor."""
        ids = self.replay_shards.healthy_actor_ids()
        if not ids:
            # Every shard died between probes: replace the whole fleet
            # (empty) rather than deadlocking.
            self.replay_shards.probe_unhealthy()
            ids = self.replay_shards.healthy_actor_ids()
        k = self._next_shard % max(len(ids), 1)
        return ids[k:] + ids[:k]

    def _total_replay_size(self) -> int:
        res = self.replay_shards.foreach(lambda a: a.size.remote(),
                                         timeout_s=10.0)
        return sum(v for _, v in res.ok)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        rollout = self.env_runner_group.sample(
            cfg.rollout_fragment_length, epsilon=self._epsilon())
        self._env_steps += len(rollout)

        # Scatter: this step's experience goes to the next shard
        # (round-robin at rollout granularity). Fire-and-forget with a
        # bounded in-flight window — the learner must not stall on
        # replay ingestion (the Ape-X decoupling).
        ids = self._rr_shard_ids()
        if ids:
            shard = self.replay_shards.actor(ids[0])
            self._next_shard += 1
            try:
                self._pending_adds.append(
                    shard.add.remote(dict(rollout.items())))
            except Exception:
                self.replay_shards._mark_unhealthy(
                    ids[0], RuntimeError("add failed"))
        if len(self._pending_adds) > 2 * cfg.num_replay_shards:
            drain, self._pending_adds = (
                self._pending_adds[:-cfg.num_replay_shards],
                self._pending_adds[-cfg.num_replay_shards:])
            try:
                ray_tpu.wait(drain, num_returns=len(drain), timeout=10.0)
            except Exception:
                pass

        # Replace killed shards (they come back EMPTY; priorities and
        # contents are experience, not state — regenerated by sampling).
        restored = self.replay_shards.probe_unhealthy()

        metrics: Dict[str, float] = {
            "epsilon": self._epsilon(),
            "replay_shards_healthy":
                self.replay_shards.num_healthy_actors(),
            "replay_shards_restored": len(restored),
        }
        total = self._total_replay_size()
        metrics["replay_size"] = total
        if total >= cfg.num_steps_sampled_before_learning_starts:
            for _ in range(cfg.updates_per_step):
                got = None
                for sid in self._rr_shard_ids():
                    shard = self.replay_shards.actor(sid)
                    try:
                        size = ray_tpu.get(shard.size.remote(),
                                           timeout=10.0)
                        if size < cfg.train_batch_size:
                            continue
                        got = (sid, shard, ray_tpu.get(
                            shard.sample.remote(cfg.train_batch_size),
                            timeout=10.0))
                        break
                    except Exception as e:
                        # Shard died mid-loop (the FT path under test):
                        # mark it and try the next one.
                        self.replay_shards._mark_unhealthy(sid, e)
                self._next_shard += 1
                if got is None:
                    break  # no shard has a full batch yet
                sid, shard, batch = got
                m = self._learner.update_dqn(batch)
                td_abs = m.pop("td_abs", None)
                if td_abs is not None and "batch_indexes" in batch:
                    try:
                        shard.update_priorities.remote(
                            batch["batch_indexes"], td_abs)
                    except Exception as e:
                        self.replay_shards._mark_unhealthy(sid, e)
                metrics.update(m)
            if self._env_steps - self._last_target_sync >= \
                    cfg.target_network_update_freq:
                self._learner.sync_target(cfg.tau)
                self._last_target_sync = self._env_steps
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
        return metrics

    def cleanup(self) -> None:
        try:
            self.replay_shards.shutdown()
        finally:
            super().cleanup()
