"""SAC — Soft Actor-Critic for continuous control.

Reference: rllib/algorithms/sac/ (SAC/SACConfig: squashed-Gaussian actor,
twin Q critics with min-target, polyak-averaged target networks, and
automatic entropy-temperature tuning against target_entropy=-act_dim).
The whole update — critic TD, actor, and alpha losses with the right
stop-gradients — is ONE jit-compiled JAX step; target params thread
through the batch like DQN's (keeps the step pure, sync stays outside).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.off_policy import OffPolicyAlgorithm
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import SACModule
from ray_tpu.rllib.utils import sample_batch as sb


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.replay_buffer_capacity: int = 100_000
        self.num_steps_sampled_before_learning_starts: int = 1_000
        self.tau: float = 0.005  # polyak factor, every update
        self.target_entropy: float = None  # default: -act_dim
        self.initial_alpha: float = 1.0
        self.rollout_fragment_length = 64
        self.train_batch_size = 256
        self.updates_per_step: int = 16
        self.lr = 3e-3

    @property
    def algo_class(self):
        return SAC


class SACLearner(JaxLearner):
    def __init__(self, module_spec, config):
        super().__init__(module_spec, config)
        import jax
        import jax.numpy as jnp

        # Targets are the critic subtrees only (actor has no target).
        self.target_params = {
            k: jax.tree_util.tree_map(lambda x: jnp.array(x, copy=True),
                                      self.params[k])
            for k in ("q1", "q2")
        }

    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        module = self.module
        gamma = cfg.get("gamma", 0.99)
        act_dim = module.act_dim
        target_entropy = cfg.get("target_entropy")
        if target_entropy is None:
            target_entropy = -float(act_dim)
        obs = batch[sb.OBS]
        next_obs = batch[sb.NEXT_OBS]
        actions = batch[sb.ACTIONS]
        if actions.ndim == 1:
            actions = actions[:, None]
        rng_next, rng_pi = jax.random.split(rng)

        alpha = jnp.exp(params["log_alpha"])

        # --- critic loss: y = r + gamma (1-d) [min Q_t(s',a') - a logp'] ---
        target = {"q1": batch["target_q1"], "q2": batch["target_q2"],
                  "pi": params["pi"], "log_alpha": params["log_alpha"]}
        next_a, next_logp = module.sample_action(params, next_obs, rng_next)
        tq1, tq2 = module.q_values(target, next_obs, next_a)
        not_done = 1.0 - batch[sb.TERMINATEDS].astype(jnp.float32)
        y = batch[sb.REWARDS] + gamma * not_done * (
            jnp.minimum(tq1, tq2) - alpha * next_logp)
        y = jax.lax.stop_gradient(y)
        q1, q2 = module.q_values(params, obs, actions)
        critic_loss = ((q1 - y) ** 2).mean() + ((q2 - y) ** 2).mean()

        # --- actor loss: E[alpha logp - min Q(s, pi(s))], critics frozen ---
        frozen_q = jax.lax.stop_gradient(
            {"q1": params["q1"], "q2": params["q2"]})
        pi_a, pi_logp = module.sample_action(params, obs, rng_pi)
        pq1, pq2 = module.q_values(
            {**params, "q1": frozen_q["q1"], "q2": frozen_q["q2"]},
            obs, pi_a)
        actor_loss = (jax.lax.stop_gradient(alpha) * pi_logp -
                      jnp.minimum(pq1, pq2)).mean()

        # --- temperature loss: drive E[-logp] toward target entropy ---
        alpha_loss = (-jnp.exp(params["log_alpha"]) *
                      jax.lax.stop_gradient(pi_logp + target_entropy)
                      ).mean()

        total = critic_loss + actor_loss + alpha_loss
        return total, {
            "critic_loss": critic_loss,
            "actor_loss": actor_loss,
            "alpha_loss": alpha_loss,
            "alpha": alpha,
            "q1_mean": q1.mean(),
            "entropy": -pi_logp.mean(),
        }

    def update_sac(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        batch = dict(batch)
        batch["target_q1"] = self.target_params["q1"]
        batch["target_q2"] = self.target_params["q2"]
        return self.update(batch)

    def _shard_batch(self, batch):
        batch = dict(batch)
        t1 = batch.pop("target_q1", None)
        t2 = batch.pop("target_q2", None)
        out = super()._shard_batch(batch)
        if t1 is not None:
            out["target_q1"] = t1
            out["target_q2"] = t2
        return out

    def sync_target(self, tau: float) -> None:
        import jax

        for k in ("q1", "q2"):
            self.target_params[k] = jax.tree_util.tree_map(
                lambda t, p: t * (1 - tau) + p * tau,
                self.target_params[k], self.params[k])

    def get_state(self):
        import jax

        state = super().get_state()
        state["target_params"] = jax.tree_util.tree_map(
            np.asarray, self.target_params)
        return state

    def set_state(self, state) -> None:
        import jax
        import jax.numpy as jnp

        super().set_state(state)
        if "target_params" in state:
            self.target_params = jax.tree_util.tree_map(
                jnp.asarray, state["target_params"])
        else:
            self.target_params = {
                k: jax.tree_util.tree_map(
                    lambda x: jnp.array(x, copy=True), self.params[k])
                for k in ("q1", "q2")
            }


class SAC(OffPolicyAlgorithm):
    config_class = SACConfig
    learner_class = SACLearner
    module_class = SACModule

    def _update_once(self, batch) -> Dict[str, float]:
        return self._learner.update_sac(batch)
