"""OffPolicyAlgorithm — shared scaffolding for replay-buffer algorithms.

Reference: the common structure of rllib's SAC/DDPG/TD3 (and DQN)
Algorithm classes: a LOCAL learner holding polyak-averaged target nets,
a driver-side replay buffer checkpointed with the algorithm, and a
training step of rollout → replay → K updates → target sync → weight
broadcast. Subclasses supply `_update_once` (one learner update from a
sampled batch).
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer


class OffPolicyAlgorithm(Algorithm):
    def setup(self, config) -> None:
        cfg = config if isinstance(config, self.config_class) else \
            self.config_class().update_from_dict(dict(config or {}))
        if cfg.num_learners != 0:
            raise ValueError(
                f"{type(self).__name__} uses a local learner "
                "(target-net state is per-learner)")
        super().setup(cfg)
        self.replay = ReplayBuffer(self.config.replay_buffer_capacity,
                                   seed=self.config.seed)
        self._env_steps = 0

    @property
    def _learner(self):
        return self.learner_group._local

    def get_extra_state(self) -> Dict[str, Any]:
        return {
            "env_steps": self._env_steps,
            "replay_cols": dict(self.replay._cols),
            "replay_size": self.replay._size,
            "replay_next": self.replay._next,
        }

    def set_extra_state(self, state: Dict[str, Any]) -> None:
        if not state:
            return
        self._env_steps = state["env_steps"]
        self.replay._cols = dict(state["replay_cols"])
        self.replay._size = state["replay_size"]
        self.replay._next = state["replay_next"]

    def _update_once(self, batch) -> Dict[str, float]:
        raise NotImplementedError

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        rollout = self.env_runner_group.sample(cfg.rollout_fragment_length)
        self._env_steps += len(rollout)
        self.replay.add(rollout)

        metrics: Dict[str, Any] = {"replay_size": len(self.replay),
                                   "num_env_steps_total": self._env_steps}
        if len(self.replay) >= \
                cfg.num_steps_sampled_before_learning_starts:
            for _ in range(cfg.updates_per_step):
                batch = self.replay.sample(cfg.train_batch_size)
                metrics.update(self._update_once(batch))
                self._learner.sync_target(cfg.tau)
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
        return metrics
