"""APPO — asynchronous-proximal PPO (IMPALA architecture + PPO clipping).

Reference: rllib/algorithms/appo/ (PPO's clipped surrogate computed
against V-trace-corrected advantages from decoupled behavior policies).
The decoupling shows up as behavior log-probs recorded at sample time —
by the time the learner consumes a rollout the weights have moved — so
advantages come from IMPALA's V-trace targets while the policy term uses
PPO's clip. Sampling here is synchronous-parallel (like this repo's
IMPALA); the off-policy correction is what carries over.
"""

from __future__ import annotations

from typing import Dict

from ray_tpu.rllib.algorithms.impala import (IMPALA, IMPALAConfig,
                                             IMPALALearner)


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.clip_param: float = 0.3
        self.num_epochs = 1

    @property
    def algo_class(self):
        return APPO


class APPOLearner(IMPALALearner):
    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        # IMPALA's loss computes V-trace targets/advantages; re-derive
        # the pieces here to swap the policy term for the PPO surrogate.
        total_impala, metrics = super().loss_fn(params, batch, rng)

        from ray_tpu.rllib.utils import sample_batch as sb

        out = self.module.forward_train(params, batch[sb.OBS])
        logits = out["action_dist_inputs"]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[sb.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None],
                                   axis=-1)[:, 0]
        behavior_logp = batch[sb.ACTION_LOGP]
        ratio = jnp.exp(logp - behavior_logp)
        # metrics carry the V-trace pg advantage via the IMPALA loss
        # internals; recompute the same stop-gradient advantage cheaply:
        # policy_loss_impala = -(logp * adv).mean()  =>  adv = -d/dlogp.
        # Instead of differentiating, re-run the shared advantage helper.
        adv = self._vtrace_advantages(params, batch)
        clip = cfg.get("clip_param", 0.3)
        surrogate = jnp.minimum(
            ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
        ppo_policy_loss = -surrogate.mean()
        # Replace IMPALA's policy term with the clipped surrogate
        # (subtract the old term out of the total, add the new one; the
        # repeated forward passes are CSE'd by XLA under jit).
        total = total_impala - metrics["policy_loss"] + ppo_policy_loss
        metrics = dict(metrics)
        metrics["policy_loss"] = ppo_policy_loss
        metrics["clip_fraction"] = (
            jnp.abs(ratio - 1.0) > clip).astype(jnp.float32).mean()
        return total, metrics

    def _vtrace_advantages(self, params, batch):
        """V-trace pg advantages (same math as IMPALALearner.loss_fn)."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.rllib.utils import sample_batch as sb

        cfg = self.config
        out = self.module.forward_train(params, batch[sb.OBS])
        values = out["vf_preds"]
        logits = out["action_dist_inputs"]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[sb.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(logp_all, actions[:, None],
                                   axis=-1)[:, 0]
        rho = jnp.exp(logp - batch[sb.ACTION_LOGP])
        rho_bar = jnp.minimum(rho,
                              cfg.get("vtrace_clip_rho_threshold", 1.0))
        c_bar = jnp.minimum(rho,
                            cfg.get("vtrace_clip_c_threshold", 1.0))
        rewards = batch[sb.REWARDS]
        boundary = batch["boundary"].astype(jnp.float32)
        next_value_override = batch["next_value_override"]
        gamma = cfg.get("gamma", 0.99)
        values_next = jnp.concatenate(
            [values[1:], jnp.zeros((1,), values.dtype)])
        values_next = jnp.where(boundary > 0, next_value_override,
                                values_next)
        not_done = 1.0 - boundary
        deltas = rho_bar * (rewards + gamma * values_next - values)

        def scan_fn(carry, xs):
            delta, c, nd = xs
            acc = delta + gamma * c * nd * carry
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            scan_fn, jnp.zeros((), values.dtype),
            (deltas, c_bar, not_done), reverse=True)
        vs = vs_minus_v + values
        vs_next = jnp.concatenate([vs[1:], jnp.zeros((1,), vs.dtype)])
        vs_next = jnp.where(boundary > 0, next_value_override, vs_next)
        adv = rho_bar * (rewards + gamma * vs_next - values)
        return jax.lax.stop_gradient(adv)


class APPO(IMPALA):
    config_class = APPOConfig
    learner_class = APPOLearner

    def training_step(self) -> Dict:
        return super().training_step()
