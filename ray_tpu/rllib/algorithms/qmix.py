"""QMIX — cooperative multi-agent Q-learning with monotonic mixing.

Reference: rllib_contrib qmix (Rashid et al. 2018: per-agent utility
networks Q_i(o_i, a_i) combined by a MIXING network whose weights are
produced by hypernetworks conditioned on the GLOBAL state, constrained
non-negative so argmax_a Q_tot decomposes into per-agent argmaxes —
centralized training, decentralized execution).

TPU-first shape: agent nets + hypernet mixer + target TD are ONE
jit-compiled step over a batch of joint transitions (target params
thread through the batch, polyak sync outside the jit — the SAC/DDPG
pattern). Agents share one utility net with an agent-id one-hot input
(the standard parameter-sharing trick). Rollouts are a local env loop
inside training_step: joint transitions (all agents' obs/actions + the
team reward) must stay joint, which the per-module env-runner batches
deliberately do not preserve.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.rl_module import _mlp_apply as _mlp
from ray_tpu.rllib.core.rl_module import _mlp_init
from ray_tpu.rllib.env.registry import make_env
from ray_tpu.rllib.utils.replay_buffers import ReplayBuffer
from ray_tpu.rllib.utils.sample_batch import SampleBatch
from ray_tpu.tune.trainable import Trainable


class QMIXConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.mixing_embed_dim: int = 32
        self.hypernet_hidden: int = 64
        self.agent_hidden: tuple = (64,)
        self.replay_buffer_capacity: int = 50_000
        self.num_steps_sampled_before_learning_starts: int = 200
        self.epsilon_start: float = 1.0
        self.epsilon_end: float = 0.05
        self.epsilon_decay_steps: int = 2_000
        self.tau: float = 0.01
        self.rollout_fragment_length = 64
        self.train_batch_size = 128
        self.updates_per_step: int = 8
        self.lr = 5e-3

    @property
    def algo_class(self):
        return QMIX




def _make_agent_qs(n_agents: int):
    """Standalone per-agent utility forward ([A, obs] -> [A, actions]):
    shared net + agent-id one-hot. Module-level so rollout workers can
    receive it pickled."""
    def agent_qs(params, obs_stack):
        import jax.numpy as jnp

        eye = jnp.eye(n_agents)
        x = jnp.concatenate([obs_stack, eye], axis=-1)
        return _mlp(params["agent"], x)

    return agent_qs


class QMIXRolloutWorker:
    """Remote joint-episode collector: steps a private env copy with
    epsilon-greedy actions from shipped params and returns JOINT
    transition columns (all agents' obs/actions + the team reward) —
    the jointness the per-module multi-agent runner batches discard."""

    def __init__(self, config: dict, worker_index: int):
        import jax

        self.config = config
        self.env = make_env(config["env"], config.get("env_config"))
        self.agents = list(self.env.agent_ids)
        self.n_agents = len(self.agents)
        self.n_actions = int(self.env.action_space_of(self.agents[0]).n)
        seed = config.get("seed", 0) * 1000 + worker_index
        self._rng = np.random.default_rng(seed)
        self._act_fn = None
        self._agent_qs = config["agent_qs_fn"]
        self._obs, _ = self.env.reset(seed=seed)
        self._episode_return = 0.0

    def collect(self, params, n_steps: int, epsilon: float):
        import jax

        if self._act_fn is None:
            self._act_fn = jax.jit(
                lambda p, o: self._agent_qs(p, o).argmax(-1))
        cols: Dict[str, list] = {k: [] for k in
                                 ("obs", "actions", "rewards",
                                  "next_obs", "dones")}
        episode_returns: list = []
        for _ in range(n_steps):
            stack = np.stack([self._obs[a] for a in self.agents])
            greedy = np.asarray(self._act_fn(params, stack))
            actions = {}
            for i, a in enumerate(self.agents):
                actions[a] = int(self._rng.integers(self.n_actions)) \
                    if self._rng.random() < epsilon else int(greedy[i])
            nxt, rewards, terms, truncs, _ = self.env.step(actions)
            team = float(rewards[self.agents[0]])
            done = bool(terms.get("__all__") or truncs.get("__all__"))
            cols["obs"].append(stack)
            cols["actions"].append(
                np.array([actions[a] for a in self.agents], np.int32))
            cols["rewards"].append(np.float32(team))
            cols["next_obs"].append(
                np.stack([nxt[a] for a in self.agents]))
            cols["dones"].append(
                np.float32(terms.get("__all__", False)))
            self._episode_return += team
            if done:
                episode_returns.append(self._episode_return)
                self._episode_return = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        return ({k: np.stack(v) for k, v in cols.items()},
                episode_returns)

    def ping(self) -> bool:
        return True


class QMIX(Trainable):
    config_class = QMIXConfig

    def setup(self, config) -> None:
        import jax
        import optax

        self.config = config if isinstance(config, QMIXConfig) else \
            QMIXConfig().update_from_dict(dict(config or {}))
        cfg = self.config
        self.env = make_env(cfg.env, cfg.env_config)
        self.agents = list(self.env.agent_ids)
        self.n_agents = len(self.agents)
        self.obs_dim = int(
            self.env.observation_space_of(self.agents[0]).shape[0])
        self.n_actions = int(self.env.action_space_of(self.agents[0]).n)
        self.state_dim = self.obs_dim * self.n_agents  # global state

        rng = jax.random.PRNGKey(cfg.seed)
        k_agent, k_w1, k_b1, k_w2, k_b2 = jax.random.split(rng, 5)
        embed = cfg.mixing_embed_dim
        hyper = cfg.hypernet_hidden
        self.params = {
            # Shared utility net over [obs ++ agent one-hot].
            "agent": _mlp_init(k_agent,
                               (self.obs_dim + self.n_agents,
                                *cfg.agent_hidden, self.n_actions)),
            # Hypernetworks: state -> mixer weights (abs() at use).
            "hyper_w1": _mlp_init(k_w1, (self.state_dim, hyper,
                                         self.n_agents * embed)),
            "hyper_b1": _mlp_init(k_b1, (self.state_dim, embed)),
            "hyper_w2": _mlp_init(k_w2, (self.state_dim, hyper, embed)),
            "hyper_b2": _mlp_init(k_b2, (self.state_dim, embed, 1)),
        }
        import jax.numpy as jnp

        self.target_params = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), self.params)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(cfg.grad_clip),
            optax.adam(cfg.lr))
        self.opt_state = self.optimizer.init(self.params)
        self._step_fn = None
        self._act_fn = None
        self._replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                    seed=cfg.seed)
        self._explore_rng = np.random.default_rng(cfg.seed)
        # Distributed joint rollouts (num_env_runners > 0): remote
        # collectors return joint transition columns; the driver keeps
        # only learning. Env stepping then parallelizes like the other
        # algorithms' runner groups.
        self._worker_manager = None
        if cfg.num_env_runners > 0:
            import ray_tpu
            from ray_tpu.rllib.utils.actor_manager import \
                FaultTolerantActorManager

            worker_cfg = {
                "env": cfg.env, "env_config": cfg.env_config,
                "seed": cfg.seed,
                "agent_qs_fn": _make_agent_qs(self.n_agents),
            }
            cls = ray_tpu.remote(QMIXRolloutWorker)

            def factory(i: int):
                return cls.options(
                    num_cpus=cfg.num_cpus_per_env_runner,
                    max_restarts=1).remote(worker_cfg, i + 1)

            self._worker_manager = FaultTolerantActorManager(
                [factory(i) for i in range(cfg.num_env_runners)],
                factory)
        self._env_steps = 0
        self._iteration = 0
        self._recent_team_returns: list = []
        self._obs, _ = self.env.reset(seed=cfg.seed)
        self._episode_return = 0.0

    # ---- policy ----

    def _agent_qs(self, params, obs_stack):
        """obs_stack [A, obs_dim] -> per-agent Q values [A, n_actions]."""
        if getattr(self, "_agent_qs_fn", None) is None:
            self._agent_qs_fn = _make_agent_qs(self.n_agents)
        return self._agent_qs_fn(params, obs_stack)

    def _mix(self, params, agent_q, state):
        """Monotonic mixer: agent_q [B, A], state [B, S] -> Q_tot [B]."""
        import jax.numpy as jnp

        embed = self.config.mixing_embed_dim
        w1 = jnp.abs(_mlp(params["hyper_w1"], state)).reshape(
            -1, self.n_agents, embed)
        b1 = _mlp(params["hyper_b1"], state)
        import jax

        hidden = jax.nn.elu(
            jnp.einsum("ba,bae->be", agent_q, w1) + b1)
        w2 = jnp.abs(_mlp(params["hyper_w2"], state))
        b2 = _mlp(params["hyper_b2"], state)[..., 0]
        return jnp.einsum("be,be->b", hidden, w2) + b2

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_start + frac * (cfg.epsilon_end -
                                           cfg.epsilon_start)

    def _act(self, obs: Dict[str, np.ndarray], epsilon: float
             ) -> Dict[str, int]:
        import jax

        if self._act_fn is None:
            self._act_fn = jax.jit(
                lambda p, o: self._agent_qs(p, o).argmax(-1))
        stack = np.stack([obs[a] for a in self.agents])
        greedy = np.asarray(self._act_fn(self.params, stack))
        out = {}
        rng = self._explore_rng
        for i, a in enumerate(self.agents):
            if rng.random() < epsilon:
                out[a] = int(rng.integers(self.n_actions))
            else:
                out[a] = int(greedy[i])
        return out

    # ---- learning ----

    def _loss(self, params, batch):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        target = batch["target_params"]
        B = batch["obs"].shape[0]

        def q_taken(p, obs, actions):
            qs = jax.vmap(lambda o: self._agent_qs(p, o))(obs)  # [B,A,N]
            return jnp.take_along_axis(
                qs, actions[..., None], axis=-1)[..., 0]       # [B,A]

        q = q_taken(params, batch["obs"], batch["actions"])
        q_tot = self._mix(params, q, batch["obs"].reshape(B, -1))

        next_qs = jax.vmap(
            lambda o: self._agent_qs(target, o))(batch["next_obs"])
        next_max = next_qs.max(-1)                             # [B,A]
        next_tot = self._mix(target, next_max,
                             batch["next_obs"].reshape(B, -1))
        y = jax.lax.stop_gradient(
            batch["rewards"] + cfg.gamma *
            (1.0 - batch["dones"]) * next_tot)
        loss = ((q_tot - y) ** 2).mean()
        return loss, {"td_loss": loss, "q_tot_mean": q_tot.mean()}

    def _update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax

        if self._step_fn is None:
            def step(params, opt_state, batch):
                (loss, metrics), grads = jax.value_and_grad(
                    self._loss, has_aux=True)(params, batch)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params)
                import optax

                params = optax.apply_updates(params, updates)
                return params, opt_state, metrics

            self._step_fn = jax.jit(step)
        batch = dict(batch)
        batch["target_params"] = self.target_params
        self.params, self.opt_state, metrics = self._step_fn(
            self.params, self.opt_state, batch)
        return {k: float(v) for k, v in metrics.items()}

    def _sync_target(self, tau: float) -> None:
        import jax

        self.target_params = jax.tree_util.tree_map(
            lambda t, p: t * (1 - tau) + p * tau,
            self.target_params, self.params)

    # ---- Trainable ----

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        eps = self._epsilon()
        if self._worker_manager is not None:
            return self._training_step_distributed(eps)
        frag: Dict[str, list] = {k: [] for k in
                                 ("obs", "actions", "rewards",
                                  "next_obs", "dones")}
        for _ in range(cfg.rollout_fragment_length):
            actions = self._act(self._obs, eps)
            nxt, rewards, terms, truncs, _ = self.env.step(actions)
            team = float(rewards[self.agents[0]])
            done = bool(terms.get("__all__") or truncs.get("__all__"))
            frag["obs"].append(
                np.stack([self._obs[a] for a in self.agents]))
            frag["actions"].append(
                np.array([actions[a] for a in self.agents], np.int32))
            frag["rewards"].append(np.float32(team))
            frag["next_obs"].append(
                np.stack([nxt[a] for a in self.agents]))
            frag["dones"].append(
                np.float32(terms.get("__all__", False)))
            self._episode_return += team
            self._env_steps += 1
            if done:
                self._recent_team_returns.append(self._episode_return)
                self._recent_team_returns = \
                    self._recent_team_returns[-100:]
                self._episode_return = 0.0
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        self._replay.add(SampleBatch(
            {k: np.stack(v) for k, v in frag.items()}))
        return self._learn_and_finish(eps)

    def _learn_and_finish(self, eps: float,
                          extra: Optional[Dict[str, Any]] = None
                          ) -> Dict[str, Any]:
        """Shared tail of both rollout paths: metrics + the learn loop."""
        cfg = self.config
        metrics: Dict[str, Any] = {
            "epsilon": eps,
            "num_env_steps_total": self._env_steps,
            "replay_size": len(self._replay),
            "episode_return_mean":
                float(np.mean(self._recent_team_returns))
                if self._recent_team_returns else float("nan"),
        }
        metrics.update(extra or {})
        if len(self._replay) >= \
                cfg.num_steps_sampled_before_learning_starts:
            for _ in range(cfg.updates_per_step):
                batch = dict(self._replay.sample(cfg.train_batch_size))
                metrics.update(self._update(batch))
                self._sync_target(cfg.tau)
        self._iteration += 1
        metrics["training_iteration"] = self._iteration
        return metrics

    def _training_step_distributed(self, eps: float) -> Dict[str, Any]:
        import jax

        import ray_tpu

        cfg = self.config
        mgr = self._worker_manager
        mgr.probe_unhealthy()  # restore dead collectors (params ship
        # per call, so restored workers need no extra state sync)
        ids = mgr.healthy_actor_ids()
        if not ids:
            raise RuntimeError("all QMIX rollout workers are dead")
        # Exact split: frag steps total, remainder spread (+1 each to
        # the first frag%n workers); workers with 0 steps are skipped.
        frag, n = cfg.rollout_fragment_length, len(ids)
        shards = {wid: frag // n + (1 if k < frag % n else 0)
                  for k, wid in enumerate(ids)}
        params_ref = ray_tpu.put(
            jax.tree_util.tree_map(np.asarray, self.params))
        results = mgr.foreach_sharded(
            lambda a, steps: a.collect.remote(params_ref, steps, eps),
            {wid: s for wid, s in shards.items() if s > 0})
        for _, (cols, episode_returns) in results.ok:
            self._replay.add(SampleBatch(cols))
            self._env_steps += len(cols["rewards"])
            self._recent_team_returns.extend(episode_returns)
        self._recent_team_returns = self._recent_team_returns[-100:]
        return self._learn_and_finish(
            eps, {"num_env_runners": mgr.num_healthy_actors()})

    def _compact_replay(self) -> Dict[str, np.ndarray]:
        """Filled replay rows, oldest-first (unwraps the ring)."""
        buf = self._replay
        if buf._size == 0:
            return {}
        if buf._size < buf.capacity:
            idx = np.arange(buf._size)
        else:
            idx = (buf._next + np.arange(buf.capacity)) % buf.capacity
        return {k: v[idx] for k, v in buf._cols.items()}

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        import os
        import pickle

        import jax

        state = {
            "params": jax.tree_util.tree_map(np.asarray, self.params),
            "target_params": jax.tree_util.tree_map(
                np.asarray, self.target_params),
            # Optimizer moments + replay contents: the learning state
            # resumes where it paused (repo convention:
            # JaxLearner.get_state / OffPolicyAlgorithm). Replay is
            # stored COMPACT (filled rows in ring order) — a
            # capacity-sized dump would pickle mostly zeros.
            "opt_state": jax.tree_util.tree_map(
                np.asarray, self.opt_state),
            "replay_rows": self._compact_replay(),
            "recent_team_returns": list(self._recent_team_returns),
            "env_steps": self._env_steps,
            "iteration": self._iteration,
        }
        with open(os.path.join(checkpoint_dir, "qmix_state.pkl"),
                  "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        import jax.numpy as jnp
        import jax

        with open(os.path.join(checkpoint_dir, "qmix_state.pkl"),
                  "rb") as f:
            state = pickle.load(f)
        self.params = jax.tree_util.tree_map(jnp.asarray, state["params"])
        self.target_params = jax.tree_util.tree_map(
            jnp.asarray, state["target_params"])
        if "opt_state" in state:
            self.opt_state = jax.tree_util.tree_map(
                jnp.asarray, state["opt_state"])
        else:
            self.opt_state = self.optimizer.init(self.params)
        rows = state.get("replay_rows")
        if rows:
            self._replay = ReplayBuffer(
                self.config.replay_buffer_capacity,
                seed=self.config.seed)
            self._replay.add(SampleBatch(rows))
        self._recent_team_returns = list(
            state.get("recent_team_returns", []))
        self._env_steps = state["env_steps"]
        self._iteration = state["iteration"]
        self._step_fn = None
        self._act_fn = None

    def cleanup(self) -> None:
        if self._worker_manager is not None:
            self._worker_manager.shutdown()
            self._worker_manager = None

    stop = cleanup

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Greedy (decentralized-execution) evaluation on a FRESH env
        instance — the training env's episode state (self._obs, clock)
        must not be disturbed mid-rollout (repo convention:
        Algorithm.evaluate uses dedicated eval runners)."""
        env = make_env(self.config.env, self.config.env_config)
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=10_000 + ep)
            total, done = 0.0, False
            while not done:
                actions = self._act(obs, epsilon=0.0)
                obs, rewards, terms, truncs, _ = env.step(actions)
                total += float(rewards[self.agents[0]])
                done = bool(terms.get("__all__") or
                            truncs.get("__all__"))
            returns.append(total)
        return {"evaluation": {
            "episode_return_mean": float(np.mean(returns)),
            "num_episodes": num_episodes}}
