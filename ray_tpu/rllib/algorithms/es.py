"""ES / ARS — gradient-free evolution algorithms.

Reference: rllib_contrib ES (OpenAI Evolution Strategies: antithetic
Gaussian parameter perturbations, centered-rank fitness shaping, SGD on
the score-function estimate) and ARS (Augmented Random Search: top-k
direction selection, update scaled by the selected returns' std).

Architecture here: the policy stays a JAX RLModule, but no gradients
flow — each training_step fans perturbation SEEDS out to the env-runner
group (`EnvRunnerGroup.evaluate_perturbations`), runners regenerate the
noise locally (shared-noise-by-seed, nothing but ints on the wire) and
return antithetic-pair returns; the driver reconstructs the same noise
to apply the update. The LearnerGroup serves as the parameter store so
checkpointing/evaluation ride the standard Algorithm paths.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule


class _ParamStoreLearner(JaxLearner):
    """Parameter store only — ES/ARS never compute a gradient."""

    def loss_fn(self, params, batch, rng):
        raise RuntimeError("ES/ARS are gradient-free: loss_fn unused")


def centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping (reference ES: compute_centered_ranks) — map
    returns to ranks in [-0.5, 0.5]; makes the update invariant to
    reward scale and robust to outliers."""
    flat = x.ravel()
    ranks = np.empty(flat.size, dtype=np.float64)
    ranks[flat.argsort()] = np.arange(flat.size)
    if flat.size > 1:
        ranks = ranks / (flat.size - 1) - 0.5
    else:
        ranks[:] = 0.0
    return ranks.reshape(x.shape)


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.num_perturbations: int = 16   # antithetic PAIRS per iter
        self.es_stdev: float = 0.05        # perturbation scale sigma
        self.es_step_size: float = 0.1     # SGD step on the estimate
        self.es_weight_decay: float = 0.0
        self.episodes_per_perturbation: int = 1

    @property
    def algo_class(self):
        return ES


class ES(Algorithm):
    config_class = ESConfig
    learner_class = _ParamStoreLearner
    module_class = DiscreteMLPModule

    def setup(self, config) -> None:
        super().setup(config)
        self._next_seed = int(self.config.seed) * 1_000_000 + 1

    def _draw_seeds(self) -> list:
        n = int(self.config.num_perturbations)
        seeds = list(range(self._next_seed, self._next_seed + n))
        self._next_seed += n
        return seeds

    def _flat_params(self):
        from jax.flatten_util import ravel_pytree

        params = self.learner_group.get_weights()
        flat, unravel = ravel_pytree(params)
        return np.asarray(flat, np.float64), unravel

    def _noise(self, seed: int, dim: int) -> np.ndarray:
        return np.random.default_rng(int(seed)).standard_normal(
            dim).astype(np.float64)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        seeds = self._draw_seeds()
        params = self.learner_group.get_weights()
        results = self.env_runner_group.evaluate_perturbations(
            params, seeds, cfg.es_stdev,
            cfg.episodes_per_perturbation)

        flat, unravel = self._flat_params()
        returns = np.array([[rp, rn] for _, rp, rn in results],
                           np.float64)
        weights = centered_ranks(returns)
        w = weights[:, 0] - weights[:, 1]            # antithetic pairs
        grad = np.zeros_like(flat)
        for (seed, _, _), wi in zip(results, w):
            grad += wi * self._noise(seed, flat.size)
        grad /= max(1, len(results)) * cfg.es_stdev

        new_flat = flat + cfg.es_step_size * grad \
            - cfg.es_step_size * cfg.es_weight_decay * flat
        self._set_flat(new_flat, unravel)
        return {
            "es_return_mean": float(returns.mean()),
            "es_return_max": float(returns.max()),
            "num_perturbation_pairs": len(results),
        }

    def _set_flat(self, new_flat: np.ndarray, unravel) -> None:
        import jax.numpy as jnp

        self.learner_group.set_weights(
            unravel(jnp.asarray(new_flat, jnp.float32)))
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights())

    def get_extra_state(self) -> Dict[str, Any]:
        return {"next_seed": self._next_seed}

    def set_extra_state(self, state: Dict[str, Any]) -> None:
        self._next_seed = state.get("next_seed", self._next_seed)


class ARSConfig(ESConfig):
    def __init__(self):
        super().__init__()
        self.top_directions: int = 8  # k best of num_perturbations

    @property
    def algo_class(self):
        return ARS


class ARS(ES):
    """Augmented Random Search (V1-t): keep only the top-k directions
    by max(r+, r-) and scale the step by the std of their returns."""

    config_class = ARSConfig

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        seeds = self._draw_seeds()
        params = self.learner_group.get_weights()
        results = self.env_runner_group.evaluate_perturbations(
            params, seeds, cfg.es_stdev,
            cfg.episodes_per_perturbation)

        k = min(int(cfg.top_directions), len(results))
        ranked = sorted(results, key=lambda t: max(t[1], t[2]),
                        reverse=True)[:k]
        sel = np.array([[rp, rn] for _, rp, rn in ranked], np.float64)
        sigma_r = float(sel.std()) or 1.0

        flat, unravel = self._flat_params()
        grad = np.zeros_like(flat)
        for seed, rp, rn in ranked:
            grad += (rp - rn) * self._noise(seed, flat.size)
        grad /= k * sigma_r

        new_flat = flat + cfg.es_step_size * grad \
            - cfg.es_step_size * cfg.es_weight_decay * flat
        self._set_flat(new_flat, unravel)
        all_returns = np.array([[rp, rn] for _, rp, rn in results])
        return {
            "es_return_mean": float(all_returns.mean()),
            "es_return_max": float(all_returns.max()),
            "ars_sigma_r": sigma_r,
            "num_perturbation_pairs": len(results),
            "num_top_directions": k,
        }
