"""DreamerV3 — model-based RL: learn a world model, act in imagination.

Reference: rllib/algorithms/dreamerv3/ (world-model RSSM + actor/critic
trained on imagined trajectories; the reference implementation likewise
runs its OWN env-stepping stack because the policy is recurrent — RSSM
state threads through the rollout, which the stateless env-runner
interface cannot carry).

JAX implementation of the core DreamerV3 recipe for vector observations
and discrete actions:

- RSSM world model: GRU deterministic core + grouped categorical
  stochastic latents (straight-through gradients, 1% unimix), obs
  encoder/decoder, reward and continue heads. Symlog targets for
  obs/reward; KL with free bits, split into dynamics (posterior
  stop-grad) and representation (prior stop-grad) terms.
- Imagination: H-step rollouts from posterior states under the actor;
  lambda-returns with a slow (EMA) critic bootstrap; critic regresses
  symlog lambda-returns; actor is REINFORCE with percentile-normalized
  returns and an entropy bonus.
- Sequence replay buffer (per-env episodes, is_first flags).

Simplifications vs the paper, stated: MSE-on-symlog critic/reward heads
instead of twohot discretized regression, and MLP encoders only (vector
observations). The training schedule, losses, and normalization follow
the paper.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        # world model
        self.deter_size: int = 128
        self.stoch_groups: int = 4
        self.stoch_classes: int = 8
        self.units: int = 128
        self.kl_free_bits: float = 1.0
        self.kl_dyn_scale: float = 0.5
        self.kl_rep_scale: float = 0.1
        # actor-critic (imagination)
        self.imagine_horizon: int = 10
        self.lambda_: float = 0.95
        self.gamma = 0.99
        self.entropy_coeff: float = 3e-3
        self.critic_ema_decay: float = 0.98
        # replay / schedule
        self.sequence_length: int = 16
        self.batch_size_sequences: int = 16
        self.replay_capacity_steps: int = 100_000
        self.env_steps_per_iteration: int = 64
        self.train_updates_per_iteration: int = 2
        self.num_steps_before_learning: int = 300
        self.model_lr: float = 1e-3
        self.actor_lr: float = 3e-4
        self.critic_lr: float = 3e-4
        self.num_envs_per_runner = 8

    @property
    def algo_class(self):
        return DreamerV3


# ----------------------------------------------------------- replay buffer
class SequenceReplay:
    """Per-env contiguous step storage; samples fixed-length
    subsequences with is_first flags (reference: dreamerv3's episode
    replay)."""

    def __init__(self, capacity_steps: int, num_envs: int, seed: int = 0):
        self.cap = max(1, capacity_steps // max(1, num_envs))
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self._cols: Dict[str, List[np.ndarray]] = {}
        self._size = 0
        self._next = 0

    def add_batch(self, step: Dict[str, np.ndarray]) -> None:
        """step: column -> [num_envs, ...] for ONE env step."""
        if not self._cols:
            for k, v in step.items():
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.cap, *v.shape), v.dtype)
        i = self._next
        for k, v in step.items():
            self._cols[k][i] = v
        self._next = (self._next + 1) % self.cap
        self._size = min(self._size + 1, self.cap)

    def __len__(self) -> int:
        return self._size * self.num_envs

    def sample(self, batch: int, length: int) -> Dict[str, np.ndarray]:
        """[batch, length, ...] subsequences (random env lane + offset).
        Sequences never span the ring's write head."""
        assert self._size > length
        out: Dict[str, List[np.ndarray]] = {k: [] for k in self._cols}
        for _ in range(batch):
            env = int(self._rng.integers(self.num_envs))
            # Valid starts avoid wrapping through the write pointer.
            if self._size < self.cap:
                start = int(self._rng.integers(0, self._size - length))
            else:
                off = int(self._rng.integers(0, self.cap - length))
                start = (self._next + off) % self.cap
            idx = [(start + t) % self.cap for t in range(length)]
            for k, col in self._cols.items():
                out[k].append(col[idx, env])
        return {k: np.stack(v) for k, v in out.items()}


# ----------------------------------------------------------- learner (jax)
class DreamerV3Learner:
    """World model + actor + critic, one jitted update."""

    def __init__(self, obs_dim: int, num_actions: int, cfg: dict):
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        D, G, C, U = (cfg["deter_size"], cfg["stoch_groups"],
                      cfg["stoch_classes"], cfg["units"])
        Z = G * C
        rng = jax.random.PRNGKey(cfg.get("seed", 0))

        def mlp_init(key, sizes):
            layers = []
            keys = jax.random.split(key, len(sizes) - 1)
            for k, fi, fo in zip(keys, sizes[:-1], sizes[1:]):
                layers.append({
                    "w": jax.random.normal(k, (fi, fo)) * np.sqrt(2.0 / fi),
                    "b": jnp.zeros((fo,))})
            return layers

        ks = jax.random.split(rng, 12)
        self.wm_params = {
            "enc": mlp_init(ks[0], [obs_dim, U, U]),
            # GRU over [z, a] -> deter
            "gru_x": mlp_init(ks[1], [Z + num_actions, U]),
            "gru": {"wz": jax.random.normal(ks[2], (U + D, D)) * 0.02,
                    "bz": jnp.zeros((D,)),
                    "wr": jax.random.normal(ks[3], (U + D, D)) * 0.02,
                    "br": jnp.zeros((D,)),
                    "wh": jax.random.normal(ks[4], (U + D, D)) * 0.02,
                    "bh": jnp.zeros((D,))},
            "prior": mlp_init(ks[5], [D, U, Z]),
            "post": mlp_init(ks[6], [D + U, U, Z]),
            "dec": mlp_init(ks[7], [D + Z, U, obs_dim]),
            "rew": mlp_init(ks[8], [D + Z, U, 1]),
            "cont": mlp_init(ks[9], [D + Z, U, 1]),
        }
        self.actor_params = mlp_init(ks[10], [D + Z, U, num_actions])
        self.critic_params = mlp_init(ks[11], [D + Z, U, 1])
        self.slow_critic = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), self.critic_params)
        self.wm_opt = optax.adam(cfg["model_lr"])
        self.ac_opt = optax.adam(cfg["actor_lr"])
        self.cr_opt = optax.adam(cfg["critic_lr"])
        self.wm_opt_state = self.wm_opt.init(self.wm_params)
        self.ac_opt_state = self.ac_opt.init(self.actor_params)
        self.cr_opt_state = self.cr_opt.init(self.critic_params)
        self._rng = jax.random.PRNGKey(cfg.get("seed", 0) + 1)
        # Percentile return-normalization EMA (paper sec. "returns").
        self.ret_lo = jnp.zeros(())
        self.ret_hi = jnp.ones(())
        self._train_jit = jax.jit(self._train_step)
        self._policy_jit = jax.jit(self._policy_step)

    # ---- building blocks (pure) ----
    @staticmethod
    def _mlp(layers, x, act_last=False):
        import jax.numpy as jnp

        for i, l in enumerate(layers):
            x = x @ l["w"] + l["b"]
            if i < len(layers) - 1 or act_last:
                x = jnp.tanh(x)
        return x

    @staticmethod
    def _symlog(x):
        import jax.numpy as jnp

        return jnp.sign(x) * jnp.log1p(jnp.abs(x))

    @staticmethod
    def _symexp(x):
        import jax.numpy as jnp

        return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)

    def _gru(self, p, x, h):
        import jax.numpy as jnp

        xh = jnp.concatenate([x, h], -1)
        z = jnp.clip(jnp.tanh(xh @ p["wz"] + p["bz"]) * 0.5 + 0.5, 0, 1)
        r = jnp.clip(jnp.tanh(xh @ p["wr"] + p["br"]) * 0.5 + 0.5, 0, 1)
        xrh = jnp.concatenate([x, r * h], -1)
        cand = jnp.tanh(xrh @ p["wh"] + p["bh"])
        return (1 - z) * h + z * cand

    def _latent(self, logits, key):
        """Straight-through one-hot sample from grouped categoricals with
        1% unimix (paper)."""
        import jax
        import jax.numpy as jnp

        G, C = self.cfg["stoch_groups"], self.cfg["stoch_classes"]
        logits = logits.reshape(*logits.shape[:-1], G, C)
        probs = 0.99 * jax.nn.softmax(logits, -1) + 0.01 / C
        sample = jax.random.categorical(key, jnp.log(probs), -1)
        onehot = jax.nn.one_hot(sample, C)
        st = onehot + probs - jax.lax.stop_gradient(probs)
        return st.reshape(*st.shape[:-2], G * C), jnp.log(probs)

    def _kl(self, logp_a, logp_b):
        """KL(a||b) over grouped categoricals, summed across groups."""
        import jax.numpy as jnp

        pa = jnp.exp(logp_a)
        return (pa * (logp_a - logp_b)).sum(-1).sum(-1)

    # ---- world model over a sequence ----
    def _observe(self, wm, obs_seq, act_seq, first_seq, key):
        """Roll the RSSM over [B, T, ...]; returns posterior features and
        per-step prior/post log-probs."""
        import jax
        import jax.numpy as jnp

        B, T = obs_seq.shape[:2]
        D = self.cfg["deter_size"]
        Z = self.cfg["stoch_groups"] * self.cfg["stoch_classes"]
        emb = self._mlp(wm["enc"], self._symlog(obs_seq), act_last=True)
        keys = jax.random.split(key, T)

        def step(carry, t_in):
            h, z = carry
            emb_t, act_t, first_t, k = t_in
            # Episode starts reset the recurrent state.
            mask = (1.0 - first_t)[:, None]
            h, z = h * mask, z * mask
            act_t = act_t * mask
            x = self._mlp(wm["gru_x"], jnp.concatenate([z, act_t], -1),
                          act_last=True)
            h = self._gru(wm["gru"], x, h)
            prior_logits = self._mlp(wm["prior"], h)
            post_in = jnp.concatenate([h, emb_t], -1)
            post_logits = self._mlp(wm["post"], post_in)
            z, logp_post = self._latent(post_logits, k)
            _, logp_prior = self._latent(prior_logits, k)
            return (h, z), (h, z, logp_post, logp_prior)

        h0 = jnp.zeros((B, D))
        z0 = jnp.zeros((B, Z))
        t_in = (jnp.swapaxes(emb, 0, 1), jnp.swapaxes(act_seq, 0, 1),
                jnp.swapaxes(first_seq, 0, 1), keys)
        _, (hs, zs, lp_post, lp_prior) = jax.lax.scan(step, (h0, z0), t_in)
        # [T, B, ...] -> [B, T, ...]
        sw = lambda a: jnp.swapaxes(a, 0, 1)  # noqa: E731
        return sw(hs), sw(zs), sw(lp_post), sw(lp_prior)

    def _wm_loss(self, wm, batch, key):
        import jax
        import jax.numpy as jnp

        obs = batch["obs"]
        acts = jax.nn.one_hot(batch["actions"].astype(jnp.int32),
                              self.num_actions)
        # Action that LED TO step t is a[t-1]; first steps get zeros.
        prev_act = jnp.concatenate(
            [jnp.zeros_like(acts[:, :1]), acts[:, :-1]], 1)
        hs, zs, lp_post, lp_prior = self._observe(
            wm, obs, prev_act, batch["is_first"], key)
        feat = jnp.concatenate([hs, zs], -1)
        recon = self._mlp(wm["dec"], feat)
        rew_hat = self._mlp(wm["rew"], feat)[..., 0]
        cont_logit = self._mlp(wm["cont"], feat)[..., 0]
        recon_loss = ((recon - self._symlog(obs)) ** 2).sum(-1).mean()
        rew_loss = ((rew_hat - self._symlog(batch["rewards"])) ** 2).mean()
        cont_target = 1.0 - batch["terminateds"].astype(jnp.float32)
        cont_loss = -(cont_target * jax.nn.log_sigmoid(cont_logit) +
                      (1 - cont_target) *
                      jax.nn.log_sigmoid(-cont_logit)).mean()
        free = self.cfg["kl_free_bits"]
        kl_dyn = jnp.maximum(
            self._kl(jax.lax.stop_gradient(lp_post), lp_prior), free).mean()
        kl_rep = jnp.maximum(
            self._kl(lp_post, jax.lax.stop_gradient(lp_prior)), free).mean()
        loss = (recon_loss + rew_loss + cont_loss +
                self.cfg["kl_dyn_scale"] * kl_dyn +
                self.cfg["kl_rep_scale"] * kl_rep)
        metrics = {"wm_loss": loss, "recon_loss": recon_loss,
                   "reward_loss": rew_loss, "kl_dyn": kl_dyn}
        return loss, (feat, metrics)

    # ---- imagination + actor/critic ----
    def _imagine(self, wm, actor, start_feat, key):
        import jax
        import jax.numpy as jnp

        D = self.cfg["deter_size"]
        H = self.cfg["imagine_horizon"]
        h = start_feat[..., :D]
        z = start_feat[..., D:]
        keys = jax.random.split(key, H)

        def step(carry, k):
            h, z = carry
            feat = jnp.concatenate([h, z], -1)
            logits = self._mlp(actor, feat)
            a = jax.random.categorical(k, logits, -1)
            a_oh = jax.nn.one_hot(a, self.num_actions)
            logp = jax.nn.log_softmax(logits, -1)
            x = self._mlp(wm["gru_x"], jnp.concatenate([z, a_oh], -1),
                          act_last=True)
            h2 = self._gru(wm["gru"], x, h)
            prior_logits = self._mlp(wm["prior"], h2)
            z2, _ = self._latent(prior_logits, k)
            return (h2, z2), (feat, a, logp)

        (_, _), (feats, acts, logps) = jax.lax.scan(step, (h, z), keys)
        return feats, acts, logps  # [H, N, ...]

    def _train_step(self, wm, actor, critic, slow_critic, opt_states,
                    ret_stats, batch, key):
        import jax
        import jax.numpy as jnp

        k_wm, k_im, k2 = jax.random.split(key, 3)
        wm_os, ac_os, cr_os = opt_states
        # 1. world model
        (wm_loss, (feat, wm_metrics)), wm_grads = jax.value_and_grad(
            self._wm_loss, has_aux=True)(wm, batch, k_wm)
        upd, wm_os = self.wm_opt.update(wm_grads, wm_os, wm)
        import optax

        wm = optax.apply_updates(wm, upd)
        # 2. imagination from (stop-grad) posterior states
        start = jax.lax.stop_gradient(feat.reshape(-1, feat.shape[-1]))
        wm_sg = jax.lax.stop_gradient(wm)

        ret_lo_ema, ret_hi_ema = ret_stats

        def ac_losses(actor_p, critic_p):
            feats, acts, logps = self._imagine(wm_sg, actor_p, start, k_im)
            rew = self._symexp(self._mlp(wm_sg["rew"], feats)[..., 0])
            cont = jax.nn.sigmoid(self._mlp(wm_sg["cont"], feats)[..., 0])
            disc = self.cfg["gamma"] * cont
            v_slow = self._symexp(
                self._mlp(slow_critic, feats)[..., 0])
            # lambda-returns, backwards (bootstrap with the slow critic).
            lam = self.cfg["lambda_"]

            def back(nxt, t):
                r_t, d_t, v_t = t
                ret = r_t + d_t * ((1 - lam) * v_t + lam * nxt)
                return ret, ret

            _, rets = jax.lax.scan(
                back, v_slow[-1],
                (rew[:-1], disc[:-1], v_slow[1:]), reverse=True)
            rets = jax.lax.stop_gradient(rets)          # [H-1, N]
            feats_t = feats[:-1]
            acts_t = acts[:-1]
            logps_t = logps[:-1]
            # Percentile return normalization (paper): scale by the EMA
            # of the 5-95% range, not this batch's (noisier) percentiles.
            lo = jnp.percentile(rets, 5)
            hi = jnp.percentile(rets, 95)
            v_online = self._symexp(self._mlp(critic_p, feats_t)[..., 0])
            scale = jnp.maximum(1.0, ret_hi_ema - ret_lo_ema)
            adv = (rets - v_online) / scale
            taken_logp = jnp.take_along_axis(
                logps_t, acts_t[..., None], -1)[..., 0]
            entropy = -(jnp.exp(logps_t) * logps_t).sum(-1)
            actor_loss = -(jax.lax.stop_gradient(adv) * taken_logp +
                           self.cfg["entropy_coeff"] * entropy).mean()
            v_pred = self._mlp(critic_p, feats_t)[..., 0]
            critic_loss = ((v_pred - self._symlog(rets)) ** 2).mean()
            return actor_loss + critic_loss, (
                actor_loss, critic_loss, rets.mean(), entropy.mean(),
                lo, hi)

        (_, aux), (a_grads, c_grads) = jax.value_and_grad(
            ac_losses, argnums=(0, 1), has_aux=True)(actor, critic)
        actor_loss, critic_loss, ret_mean, ent, lo, hi = aux
        upd, ac_os = self.ac_opt.update(a_grads, ac_os, actor)
        actor = optax.apply_updates(actor, upd)
        upd, cr_os = self.cr_opt.update(c_grads, cr_os, critic)
        critic = optax.apply_updates(critic, upd)
        decay = self.cfg["critic_ema_decay"]
        slow_critic = jax.tree_util.tree_map(
            lambda s, p: decay * s + (1 - decay) * p, slow_critic, critic)
        ret_lo = 0.99 * ret_stats[0] + 0.01 * lo
        ret_hi = 0.99 * ret_stats[1] + 0.01 * hi
        metrics = dict(wm_metrics)
        metrics.update({"actor_loss": actor_loss,
                        "critic_loss": critic_loss,
                        "imagined_return": ret_mean,
                        "actor_entropy": ent})
        return (wm, actor, critic, slow_critic, (wm_os, ac_os, cr_os),
                (ret_lo, ret_hi), metrics)

    def _policy_step(self, wm, actor, h, z, prev_a, first, obs, key):
        """One recurrent policy step for the env loop (posterior)."""
        import jax
        import jax.numpy as jnp

        mask = (1.0 - first)[:, None]
        h, z = h * mask, z * mask
        a_oh = jax.nn.one_hot(prev_a, self.num_actions) * mask
        x = self._mlp(wm["gru_x"], jnp.concatenate([z, a_oh], -1),
                      act_last=True)
        h = self._gru(wm["gru"], x, h)
        emb = self._mlp(wm["enc"], self._symlog(obs), act_last=True)
        post_logits = self._mlp(wm["post"],
                                jnp.concatenate([h, emb], -1))
        z, _ = self._latent(post_logits, key)
        logits = self._mlp(actor, jnp.concatenate([h, z], -1))
        a = jax.random.categorical(key, logits, -1)
        return h, z, a

    # ---- public ----
    def policy(self, h, z, prev_a, first, obs):
        import jax

        self._rng, key = jax.random.split(self._rng)
        return self._policy_jit(self.wm_params, self.actor_params,
                                h, z, prev_a, first, obs, key)

    def train(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp

        self._rng, key = jax.random.split(self._rng)
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        (self.wm_params, self.actor_params, self.critic_params,
         self.slow_critic,
         (self.wm_opt_state, self.ac_opt_state, self.cr_opt_state),
         (self.ret_lo, self.ret_hi), metrics) = self._train_jit(
            self.wm_params, self.actor_params, self.critic_params,
            self.slow_critic,
            (self.wm_opt_state, self.ac_opt_state, self.cr_opt_state),
            (self.ret_lo, self.ret_hi), jb, key)
        return {k: float(v) for k, v in metrics.items()}

    def get_state(self) -> Dict[str, Any]:
        import jax

        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa
        return {"wm": to_np(self.wm_params),
                "actor": to_np(self.actor_params),
                "critic": to_np(self.critic_params),
                "slow_critic": to_np(self.slow_critic)}

    def set_state(self, state: Dict[str, Any]) -> None:
        import jax.numpy as jnp

        as_j = lambda t: jax.tree_util.tree_map(jnp.asarray, t)  # noqa
        import jax

        self.wm_params = as_j(state["wm"])
        self.actor_params = as_j(state["actor"])
        self.critic_params = as_j(state["critic"])
        self.slow_critic = as_j(state["slow_critic"])


# ----------------------------------------------------------- algorithm
class DreamerV3(Algorithm):
    """Self-contained setup: the recurrent policy owns its env loop (the
    reference's DreamerV3 likewise subclasses the runner stack rather
    than using the stateless one)."""

    config_class = DreamerV3Config

    def setup(self, config) -> None:
        import jax.numpy as jnp

        from ray_tpu.rllib.env.vector import make_vector_env

        if isinstance(config, AlgorithmConfig):
            self.config = config
        else:
            self.config = self.config_class().update_from_dict(
                dict(config or {}))
        cfg = self.config
        self.num_envs = max(1, cfg.num_envs_per_runner)
        self.env = make_vector_env(cfg.env, cfg.env_config, self.num_envs,
                                   seed=cfg.seed)
        self.env.reset(seed=cfg.seed)
        obs_dim = int(self.env.observation_space.shape[0])
        self.num_actions = int(self.env.action_space.n)
        self.learner = DreamerV3Learner(obs_dim, self.num_actions,
                                        cfg.to_dict())
        self.replay = SequenceReplay(cfg.replay_capacity_steps,
                                     self.num_envs, seed=cfg.seed)
        D = cfg.deter_size
        Z = cfg.stoch_groups * cfg.stoch_classes
        self._h = jnp.zeros((self.num_envs, D))
        self._z = jnp.zeros((self.num_envs, Z))
        self._prev_a = np.zeros(self.num_envs, np.int32)
        self._first = np.ones(self.num_envs, np.float32)
        self._ep_ret = np.zeros(self.num_envs)
        self._recent_returns: List[float] = []
        self._env_steps = 0
        self._iteration = 0

    def step(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        results = self.training_step()
        self._iteration += 1
        results["training_iteration"] = self._iteration
        results["time_this_iter_s"] = time.perf_counter() - t0
        return results

    def training_step(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        cfg = self.config
        for _ in range(cfg.env_steps_per_iteration // self.num_envs):
            obs = self.env.current_obs
            h, z, a = self.learner.policy(
                self._h, self._z, jnp.asarray(self._prev_a),
                jnp.asarray(self._first), jnp.asarray(obs))
            actions = np.asarray(a)
            _, rewards, terms, truncs = self.env.step(actions)
            self.replay.add_batch({
                "obs": obs.astype(np.float32),
                "actions": actions.astype(np.int32),
                "rewards": rewards.astype(np.float32),
                "terminateds": terms.astype(np.float32),
                "is_first": self._first.astype(np.float32)})
            self._h, self._z = h, z
            self._prev_a = actions
            done = terms | truncs
            self._first = done.astype(np.float32)
            self._ep_ret += rewards
            for i in np.nonzero(done)[0]:
                self._recent_returns.append(float(self._ep_ret[i]))
                self._ep_ret[i] = 0.0
            self._env_steps += self.num_envs
        metrics: Dict[str, Any] = {"num_env_steps": self._env_steps}
        if len(self.replay) >= cfg.num_steps_before_learning and \
                self.replay._size > cfg.sequence_length:
            for _ in range(cfg.train_updates_per_iteration):
                batch = self.replay.sample(cfg.batch_size_sequences,
                                           cfg.sequence_length)
                metrics.update(self.learner.train(batch))
        recent = self._recent_returns[-100:]
        if recent:
            metrics["episode_return_mean"] = float(np.mean(recent))
        return metrics

    def get_extra_state(self) -> Dict[str, Any]:
        return {"env_steps": self._env_steps}

    def set_extra_state(self, state: Dict[str, Any]) -> None:
        self._env_steps = state.get("env_steps", 0)

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        import os
        import pickle

        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump({"learner": self.learner.get_state(),
                         "iteration": self._iteration,
                         "algo_state": self.get_extra_state()}, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        import os
        import pickle

        with open(os.path.join(checkpoint_dir,
                               "algorithm_state.pkl"), "rb") as f:
            state = pickle.load(f)
        self.learner.set_state(state["learner"])
        self._iteration = state["iteration"]
        self.set_extra_state(state.get("algo_state", {}))

    def cleanup(self) -> None:
        pass
