"""Algorithm — the trainable driver of an RL experiment.

Reference: rllib/algorithms/algorithm.py:229 (Algorithm extends Trainable;
step() :894 calls training_step() :1670; save/restore via Checkpointable).
Subclasses implement training_step(); Tune runs Algorithms directly
because Algorithm is a ray_tpu.tune Trainable.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Dict, Optional

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.env.env_runner_group import EnvRunnerGroup
from ray_tpu.rllib.env.registry import make_env
from ray_tpu.tune.trainable import Trainable


class Algorithm(Trainable):
    config_class = AlgorithmConfig
    learner_class: type = None  # set by subclass
    module_class: type = None   # set by subclass

    # ---- Trainable hooks ----

    def setup(self, config) -> None:
        if isinstance(config, AlgorithmConfig):
            self.config = config
        else:
            self.config = self.config_class().update_from_dict(
                dict(config or {}))
        if self.config.env is None:
            raise ValueError("config.environment(env=...) is required")
        probe = make_env(self.config.env, self.config.env_config)
        obs_dim = int(probe.observation_space.shape[0])
        # ConnectorV2 pipelines (reference: rllib/connectors/): an
        # env_to_module connector may reshape observations (e.g. frame
        # stacking) — size the module from the TRANSFORMED dim.
        from ray_tpu.rllib.connectors.connector import build_pipeline

        obs_dim = build_pipeline(
            self.config.env_to_module_connector).observation_dim(obs_dim)
        self.learner_connector_pipeline = build_pipeline(
            self.config.learner_connector)
        space = probe.action_space
        if hasattr(space, "n"):  # Discrete
            num_actions = int(space.n)
        else:  # Box: num_actions is the action DIM; bounds go to the module
            import numpy as np

            num_actions = int(np.prod(space.shape))
            model = dict(self.config.model)
            model.setdefault("action_low", np.asarray(space.low))
            model.setdefault("action_high", np.asarray(space.high))
            self.config.model = model
        self.module_spec = self._make_module_spec(obs_dim, num_actions)
        cfg = self.config.to_dict()
        cfg["module_spec"] = self.module_spec
        self.env_runner_group = EnvRunnerGroup(cfg)
        self.learner_group = LearnerGroup(
            self.learner_class, self.module_spec, cfg)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        # Dedicated evaluation runner group (reference:
        # AlgorithmConfig.evaluation() -> eval EnvRunnerGroup).
        self.eval_env_runner_group = None
        if self.config.evaluation_interval > 0:
            eval_cfg = dict(cfg)
            eval_cfg["num_env_runners"] = \
                self.config.evaluation_num_env_runners
            self.eval_env_runner_group = EnvRunnerGroup(eval_cfg)
        self._iteration = 0

    def _make_module_spec(self, obs_dim: int, num_actions: int):
        from ray_tpu.rllib.core.rl_module import RLModuleSpec

        return RLModuleSpec(self.module_class, obs_dim, num_actions,
                            dict(self.config.model))

    def apply_learner_connector(self, batch):
        """Run the learner ConnectorV2 pipeline over a sampled batch
        (reference: the learner connector runs before loss computation —
        here before advantage estimation, the same ordering the
        reference's GeneralAdvantageEstimation connector relies on)."""
        from ray_tpu.rllib.utils.sample_batch import SampleBatch

        if not len(self.learner_connector_pipeline):
            return batch
        return SampleBatch(self.learner_connector_pipeline(batch))

    def step(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        results = self.training_step()
        self._iteration += 1
        if self.config.restart_failed_env_runners:
            restored = self.env_runner_group.restore_failed(
                self.learner_group.get_weights)
            if restored:
                results["num_env_runners_restored"] = restored
        metrics = self.env_runner_group.aggregate_metrics()
        results.update(metrics)
        if self.eval_env_runner_group is not None and \
                self._iteration % self.config.evaluation_interval == 0:
            results.update(self.evaluate(self.config.evaluation_duration))
        results["training_iteration"] = self._iteration
        results["time_this_iter_s"] = time.perf_counter() - t0
        return results

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # Reference-style convenience: algo.train() loops come from Trainable.

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        state = {
            "learner": self.learner_group.get_state(),
            "iteration": self._iteration,
            "algo_state": self.get_extra_state(),
        }
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(state, f)
        return checkpoint_dir

    def load_checkpoint(self, checkpoint_dir: str) -> None:
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "rb") as f:
            state = pickle.load(f)
        self.learner_group.set_state(state["learner"])
        self._iteration = state["iteration"]
        self.set_extra_state(state.get("algo_state", {}))
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    def get_extra_state(self) -> Dict[str, Any]:
        return {}

    def set_extra_state(self, state: Dict[str, Any]) -> None:
        pass

    def cleanup(self) -> None:
        try:
            self.env_runner_group.stop()
            if self.eval_env_runner_group is not None:
                self.eval_env_runner_group.stop()
        finally:
            self.learner_group.stop()

    stop = cleanup

    # ---- evaluation ----

    def evaluate(self, num_episodes: int = 5) -> Dict[str, Any]:
        """Greedy evaluation on the dedicated eval runner group (built
        when config.evaluation_interval > 0), else an ad-hoc local one
        (reference: Algorithm.evaluate over evaluation env runners)."""
        import numpy as np

        group = self.eval_env_runner_group
        if group is None:
            cfg = self.config.to_dict()
            cfg["module_spec"] = self.module_spec
            cfg["num_env_runners"] = 0
            group = EnvRunnerGroup(cfg)
            try:
                group.sync_weights(self.learner_group.get_weights())
                returns = group.sample_episodes(num_episodes)
            finally:
                group.stop()
        else:
            group.sync_weights(self.learner_group.get_weights())
            returns = group.sample_episodes(num_episodes)
        return {"evaluation": {
            "episode_return_mean":
                float(np.mean(returns)) if returns else float("nan"),
            "num_episodes": len(returns)}}
