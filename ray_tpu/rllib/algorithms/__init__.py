"""Algorithms: PPO, APPO, IMPALA, DQN, Apex-DQN (distributed
prioritized replay), SAC, CQL, BC, MARWIL, multi-agent PPO, DreamerV3
(model-based), DDPG, TD3 (deterministic continuous control), ES, ARS
(gradient-free evolution), A2C, QMIX (monotonic mixing), AlphaZero
(self-play MCTS)."""
