"""Algorithms: PPO, DQN."""
