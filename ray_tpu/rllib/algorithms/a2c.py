"""A2C — synchronous advantage actor-critic.

Reference: rllib_contrib a2c (A2C = synchronous A3C: parallel env
runners sample a short on-policy fragment, one combined gradient step
on the n-step-advantage policy loss + value loss + entropy bonus; no
surrogate clipping, no minibatch epochs — the simple on-policy
baseline PPO refines).

Reuses the PPO plumbing (GAE from the same rollout machinery) with a
single-epoch, whole-batch vanilla policy-gradient update in one jitted
step.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule
from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.postprocessing import compute_gae, standardize
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class A2CConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lambda_: float = 1.0          # pure n-step returns
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.01
        self.train_batch_size = 512
        self.lr = 1e-3

    @property
    def algo_class(self):
        return A2C


class A2CLearner(JaxLearner):
    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        out = self.module.forward_train(params, batch[sb.OBS])
        logits = out["action_dist_inputs"]
        values = out["vf_preds"]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[sb.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(
            logp_all, actions[:, None], axis=-1)[:, 0]

        adv = batch[sb.ADVANTAGES]
        pg_loss = -(logp * adv).mean()
        vf_loss = ((values - batch[sb.VALUE_TARGETS]) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()

        total = pg_loss + cfg.get("vf_loss_coeff", 0.5) * vf_loss \
            - cfg.get("entropy_coeff", 0.01) * entropy
        return total, {
            "policy_loss": pg_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
        }


class A2C(Algorithm):
    config_class = A2CConfig
    learner_class = A2CLearner
    module_class = DiscreteMLPModule

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        pieces = self.env_runner_group.sample_with_bootstraps(
            cfg.train_batch_size)
        batches = []
        for batch, boot in pieces:
            batch = self.apply_learner_connector(batch)
            batch = compute_gae(batch, gamma=cfg.gamma,
                                lambda_=cfg.lambda_, bootstrap_value=boot)
            batches.append(batch)
        train_batch = SampleBatch.concat_samples(batches)
        train_batch[sb.ADVANTAGES] = standardize(
            train_batch[sb.ADVANTAGES])
        # ONE whole-batch step per iteration (the A2C/PPO difference).
        metrics = self.learner_group.update(train_batch)
        self.env_runner_group.sync_weights(
            self.learner_group.get_weights())
        return dict(metrics)
