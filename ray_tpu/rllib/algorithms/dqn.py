"""DQN — deep Q-learning with target network and (optional) PER.

Reference: rllib/algorithms/dqn/ (DQN new-stack: epsilon-greedy sampling
into an episode replay buffer, double-Q TD targets, periodic target-net
sync). Loss is jit-compiled JAX with a Huber TD error.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import QNetModule
from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.replay_buffers import (PrioritizedReplayBuffer,
                                                ReplayBuffer)
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.replay_buffer_capacity: int = 50_000
        self.prioritized_replay: bool = False
        self.num_steps_sampled_before_learning_starts: int = 500
        self.target_network_update_freq: int = 500  # in env steps
        self.epsilon_initial: float = 1.0
        self.epsilon_final: float = 0.05
        self.epsilon_decay_steps: int = 5_000
        self.double_q: bool = True
        self.tau: float = 1.0  # 1.0 = hard target sync
        self.rollout_fragment_length = 50
        self.train_batch_size = 32
        self.updates_per_step: int = 4

    @property
    def algo_class(self):
        return DQN


class DQNLearner(JaxLearner):
    def __init__(self, module_spec, config):
        super().__init__(module_spec, config)
        import jax
        import jax.numpy as jnp

        # Real copies: the online params are donated into the jitted step,
        # so the target tree must not alias their buffers.
        self.target_params = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), self.params)

    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        q_all = self.module.forward_train(params, batch[sb.OBS])["q_values"]
        actions = batch[sb.ACTIONS].astype(jnp.int32)
        q = jnp.take_along_axis(q_all, actions[:, None], axis=-1)[:, 0]

        q_next_target = self.module.forward_train(
            batch["target_params"], batch[sb.NEXT_OBS])["q_values"]
        if cfg.get("double_q", True):
            q_next_online = self.module.forward_train(
                params, batch[sb.NEXT_OBS])["q_values"]
            next_actions = jnp.argmax(q_next_online, axis=-1)
        else:
            next_actions = jnp.argmax(q_next_target, axis=-1)
        q_next = jnp.take_along_axis(
            q_next_target, next_actions[:, None], axis=-1)[:, 0]
        q_next = jax.lax.stop_gradient(q_next)

        not_done = 1.0 - batch[sb.TERMINATEDS].astype(jnp.float32)
        targets = batch[sb.REWARDS] + gamma * not_done * q_next
        td = q - targets
        huber = jnp.where(jnp.abs(td) < 1.0, 0.5 * td ** 2,
                          jnp.abs(td) - 0.5)
        weights = batch.get("weights")
        loss = (huber * weights).mean() if weights is not None \
            else huber.mean()
        return loss, {"td_error_mean": jnp.abs(td).mean(),
                      "td_abs": jnp.abs(td),
                      "q_mean": q.mean()}

    def update_dqn(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """update() with the target params threaded through the batch
        (keeps the jitted step pure; target sync stays outside jit)."""
        batch = dict(batch)
        batch["target_params"] = self.target_params
        return self.update(batch)

    def _shard_batch(self, batch):
        # target_params rides along unsharded.
        import jax.numpy as jnp

        batch = dict(batch)
        target = batch.pop("target_params", None)
        out = super()._shard_batch(batch)
        if target is not None:
            out["target_params"] = target
        return out

    def sync_target(self, tau: float = 1.0) -> None:
        import jax

        self.target_params = jax.tree_util.tree_map(
            lambda t, p: t * (1 - tau) + p * tau,
            self.target_params, self.params)

    def get_state(self):
        import jax

        state = super().get_state()
        state["target_params"] = jax.tree_util.tree_map(
            np.asarray, self.target_params)
        return state

    def set_state(self, state) -> None:
        import jax
        import jax.numpy as jnp

        super().set_state(state)
        if "target_params" in state:
            self.target_params = jax.tree_util.tree_map(
                jnp.asarray, state["target_params"])
        else:
            self.target_params = jax.tree_util.tree_map(
                lambda x: jnp.array(x, copy=True), self.params)


class DQN(Algorithm):
    config_class = DQNConfig
    learner_class = DQNLearner
    module_class = QNetModule

    def setup(self, config) -> None:
        # Validate before super() spawns any learner actors (a raise after
        # would leak the remote LearnerGroup).
        cfg = config if isinstance(config, DQNConfig) else \
            self.config_class().update_from_dict(dict(config or {}))
        if cfg.num_learners != 0:
            raise ValueError(
                "DQN uses a local learner (target-net state is per-learner)")
        super().setup(cfg)
        cfg = self.config
        buffer_cls = PrioritizedReplayBuffer if cfg.prioritized_replay \
            else ReplayBuffer
        self.replay = buffer_cls(cfg.replay_buffer_capacity, seed=cfg.seed)
        self._env_steps = 0
        self._last_target_sync = 0

    @property
    def _learner(self) -> DQNLearner:
        return self.learner_group._local

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final -
                                             cfg.epsilon_initial)

    def get_extra_state(self) -> Dict[str, Any]:
        state = {
            "env_steps": self._env_steps,
            "last_target_sync": self._last_target_sync,
            "replay_cols": dict(self.replay._cols),
            "replay_size": self.replay._size,
            "replay_next": self.replay._next,
        }
        if isinstance(self.replay, PrioritizedReplayBuffer):
            state["replay_priorities"] = self.replay._priorities.copy()
            state["replay_max_priority"] = self.replay._max_priority
        return state

    def set_extra_state(self, state: Dict[str, Any]) -> None:
        if not state:
            return
        self._env_steps = state["env_steps"]
        self._last_target_sync = state["last_target_sync"]
        self.replay._cols = dict(state["replay_cols"])
        self.replay._size = state["replay_size"]
        self.replay._next = state["replay_next"]
        if isinstance(self.replay, PrioritizedReplayBuffer) and \
                "replay_priorities" in state:
            self.replay._priorities = np.asarray(
                state["replay_priorities"])
            self.replay._max_priority = state["replay_max_priority"]

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        rollout = self.env_runner_group.sample(
            cfg.rollout_fragment_length, epsilon=self._epsilon())
        self._env_steps += len(rollout)
        self.replay.add(rollout)

        metrics: Dict[str, float] = {"epsilon": self._epsilon(),
                                     "replay_size": len(self.replay)}
        if len(self.replay) >= \
                cfg.num_steps_sampled_before_learning_starts:
            for _ in range(cfg.updates_per_step):
                batch = self.replay.sample(cfg.train_batch_size)
                m = self._learner.update_dqn(batch)
                td_abs = m.pop("td_abs", None)
                if cfg.prioritized_replay and "batch_indexes" in batch \
                        and td_abs is not None:
                    self.replay.update_priorities(
                        batch["batch_indexes"], td_abs)
                metrics.update(m)
            if self._env_steps - self._last_target_sync >= \
                    cfg.target_network_update_freq:
                self._learner.sync_target(cfg.tau)
                self._last_target_sync = self._env_steps
            self.env_runner_group.sync_weights(
                self.learner_group.get_weights())
        return metrics
