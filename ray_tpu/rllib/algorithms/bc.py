"""BC — behavior cloning from offline data.

Reference: rllib/algorithms/bc/ (offline RL entry point: supervised
imitation of logged actions; MARWIL with beta=0). The offline dataset is
either a dict of numpy arrays ({obs, actions}) or a ray_tpu.data
Dataset with those columns; the env is used only to size the module and
for evaluation.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule
from ray_tpu.rllib.utils import sample_batch as sb


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.offline_dataset: Any = None
        self.train_batch_size = 256
        self.num_env_runners = 0

    def offline_data(self, *, dataset=None, **kwargs) -> "BCConfig":
        if dataset is not None:
            self.offline_dataset = dataset
        self._apply(kwargs)
        return self

    def to_dict(self) -> Dict[str, Any]:
        # The dataset stays driver-side: shipping it in the worker/learner
        # construction configs would pickle the whole thing into every
        # actor for no use.
        d = super().to_dict()
        d.pop("offline_dataset", None)
        return d

    @property
    def algo_class(self):
        return BC


class BCLearner(JaxLearner):
    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        logits = self.module.forward_train(
            params, batch[sb.OBS])["action_dist_inputs"]
        logp = jax.nn.log_softmax(logits)
        actions = batch[sb.ACTIONS].astype(jnp.int32)
        nll = -jnp.take_along_axis(logp, actions[:, None], axis=-1)[:, 0]
        accuracy = (jnp.argmax(logits, -1) == actions).mean()
        return nll.mean(), {"bc_nll": nll.mean(), "accuracy": accuracy}


class BC(Algorithm):
    config_class = BCConfig
    learner_class = BCLearner
    module_class = DiscreteMLPModule

    def setup(self, config) -> None:
        super().setup(config)
        ds = self.config.offline_dataset
        if ds is None:
            raise ValueError("BCConfig.offline_data(dataset=...) required")
        if hasattr(ds, "take_all"):  # ray_tpu.data Dataset
            rows = ds.take_all()
            self._obs = np.stack([np.asarray(r["obs"]) for r in rows])
            self._actions = np.asarray([r["actions"] for r in rows])
        else:
            self._obs = np.asarray(ds["obs"])
            self._actions = np.asarray(ds["actions"])
        self._rng = np.random.default_rng(self.config.seed)

    def training_step(self) -> Dict[str, Any]:
        from ray_tpu.rllib.utils.sample_batch import SampleBatch

        n = len(self._obs)
        idx = self._rng.integers(0, n, self.config.train_batch_size)
        batch = SampleBatch({sb.OBS: self._obs[idx].astype(np.float32),
                             sb.ACTIONS: self._actions[idx]})
        # No per-step weight broadcast: BC never samples from env
        # runners (evaluate() pulls weights straight from the learners).
        # Iteration/timing bookkeeping comes from the base
        # Algorithm.step (safe with the zero-env-runner local group).
        return self.learner_group.update(batch)
