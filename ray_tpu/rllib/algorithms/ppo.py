"""PPO — Proximal Policy Optimization.

Reference: rllib/algorithms/ppo/ppo.py:401 (PPO, training_step :427:
synchronous_parallel_sample → GAE → LearnerGroup.update minibatch SGD →
weight broadcast) and ppo/torch/ppo_torch_learner.py (clipped surrogate
loss). The loss is jit-compiled JAX; rollouts are CPU actors.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import JaxLearner
from ray_tpu.rllib.core.rl_module import DiscreteMLPModule
from ray_tpu.rllib.utils import sample_batch as sb
from ray_tpu.rllib.utils.postprocessing import compute_gae, standardize
from ray_tpu.rllib.utils.sample_batch import SampleBatch


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lambda_: float = 0.95
        self.clip_param: float = 0.2
        self.vf_clip_param: float = 10.0
        self.vf_loss_coeff: float = 0.5
        self.entropy_coeff: float = 0.0
        self.kl_target: float = 0.01
        self.use_kl_loss: bool = False
        self.kl_coeff: float = 0.2

    @property
    def algo_class(self):
        return PPO


class PPOLearner(JaxLearner):
    def loss_fn(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        cfg = self.config
        out = self.module.forward_train(params, batch[sb.OBS])
        logits = out["action_dist_inputs"]
        values = out["vf_preds"]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch[sb.ACTIONS].astype(jnp.int32)
        logp = jnp.take_along_axis(
            logp_all, actions[:, None], axis=-1)[:, 0]
        old_logp = batch[sb.ACTION_LOGP]
        adv = batch[sb.ADVANTAGES]

        ratio = jnp.exp(logp - old_logp)
        clip = cfg.get("clip_param", 0.2)
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
        policy_loss = -surrogate.mean()

        # Clipped value loss (reference: ppo_torch_learner vf_loss_clipped).
        vf_err = (values - batch[sb.VALUE_TARGETS]) ** 2
        vf_loss = jnp.clip(vf_err, 0.0,
                           cfg.get("vf_clip_param", 10.0)).mean()

        probs = jax.nn.softmax(logits)
        entropy = -(probs * logp_all).sum(-1).mean()

        kl = (old_logp - logp).mean()
        total = (policy_loss +
                 cfg.get("vf_loss_coeff", 0.5) * vf_loss -
                 cfg.get("entropy_coeff", 0.0) * entropy)
        if cfg.get("use_kl_loss", False):
            total = total + cfg.get("kl_coeff", 0.2) * kl
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "kl": kl,
        }


class PPO(Algorithm):
    config_class = PPOConfig
    learner_class = PPOLearner
    module_class = DiscreteMLPModule

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        pairs = self.env_runner_group.sample_with_bootstraps(
            cfg.train_batch_size)
        train_batch = SampleBatch.concat_samples([
            compute_gae(self.apply_learner_connector(batch),
                        cfg.gamma, cfg.lambda_, bootstrap)
            for batch, bootstrap in pairs])
        train_batch[sb.ADVANTAGES] = standardize(
            train_batch[sb.ADVANTAGES])

        rng = np.random.default_rng(cfg.seed + self._iteration)
        metrics: Dict[str, float] = {}
        count = 0
        for _ in range(cfg.num_epochs):
            for minibatch in train_batch.minibatches(cfg.minibatch_size,
                                                     rng):
                m = self.learner_group.update(minibatch)
                count += 1
                for k, v in m.items():
                    metrics[k] = metrics.get(k, 0.0) + v
        metrics = {k: v / max(1, count) for k, v in metrics.items()}
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        return metrics
