from ray_tpu.rllib.connectors.connector import (ConnectorPipelineV2,
                                                ConnectorV2, EpsilonGreedy,
                                                FrameStackObs,
                                                RunningRewardNorm)

__all__ = ["ConnectorV2", "ConnectorPipelineV2", "FrameStackObs",
           "EpsilonGreedy", "RunningRewardNorm"]
