"""ConnectorV2: pluggable transform pipelines between env, module, and
learner.

Reference: rllib/connectors/connector.py (ConnectorV2 +
ConnectorPipelineV2) — the new-stack seam where observation
preprocessing, action post-processing, and train-batch transforms live,
instead of being hard-wired into env runners and learners. Three
pipelines, mirroring the reference:

- env_to_module: raw env observations -> module inputs (each rollout
  step, batched over the runner's vector envs).
- module_to_env: module outputs -> env actions (each rollout step).
- learner: sampled train batch -> loss inputs (before GAE/update —
  where the reference runs its GeneralAdvantageEstimation connector).

Connectors are stateful objects built per runner/learner from picklable
FACTORIES carried in the config (the runner is an actor in another
process). ``get_state``/``set_state`` expose synchronizable state
(e.g. running normalization statistics).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


class ConnectorV2:
    """One transform. Subclasses override __call__ and may carry state.

    __call__ receives the batch dict (column -> np.ndarray) plus keyword
    context and returns the (possibly new) batch dict. Context keys used
    by the built-in seams:

    - dones: bool[N] — which vector envs finished on the PREVIOUS step
      (env_to_module; reset per-env state there).
    - commit: bool — False for peek-style calls that must not advance
      internal state (the runner transforms next_obs for recording
      without double-advancing frame stacks).
    - explore / epsilon / action_space_n / rng — module_to_env context.
    """

    def __call__(self, batch: Dict[str, Any], **ctx) -> Dict[str, Any]:
        return batch

    def observation_dim(self, input_dim: int) -> int:
        """Transformed flat observation dim (module sizing)."""
        return input_dim

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass

    @property
    def name(self) -> str:
        return type(self).__name__


class ConnectorPipelineV2(ConnectorV2):
    """An ordered chain of connectors applied left to right."""

    def __init__(self, connectors: Optional[Sequence[ConnectorV2]] = None):
        self.connectors: List[ConnectorV2] = list(connectors or [])

    def append(self, connector: ConnectorV2) -> "ConnectorPipelineV2":
        self.connectors.append(connector)
        return self

    def __call__(self, batch: Dict[str, Any], **ctx) -> Dict[str, Any]:
        for c in self.connectors:
            batch = c(batch, **ctx)
        return batch

    def observation_dim(self, input_dim: int) -> int:
        for c in self.connectors:
            input_dim = c.observation_dim(input_dim)
        return input_dim

    def get_state(self) -> Dict[str, Any]:
        return {c.name + f"_{i}": c.get_state()
                for i, c in enumerate(self.connectors)}

    def set_state(self, state: Dict[str, Any]) -> None:
        for i, c in enumerate(self.connectors):
            key = c.name + f"_{i}"
            if key in state:
                c.set_state(state[key])

    def __len__(self) -> int:
        return len(self.connectors)


def build_pipeline(factory: Optional[Callable[[], Any]]
                   ) -> ConnectorPipelineV2:
    """Materialize a user factory into a pipeline (factories keep the
    config picklable; a factory may return one connector or a list)."""
    if factory is None:
        return ConnectorPipelineV2()
    made = factory()
    if isinstance(made, ConnectorPipelineV2):
        return made
    if isinstance(made, ConnectorV2):
        return ConnectorPipelineV2([made])
    return ConnectorPipelineV2(list(made))


# --------------------------------------------------------------- built-ins
class FrameStackObs(ConnectorV2):
    """env_to_module: stack the last k observations per vector env along
    the feature axis (reference: connectors/env_to_module/
    frame_stacking.py). State resets for an env when its episode ends.
    """

    def __init__(self, k: int = 4):
        assert k >= 1
        self.k = k
        self._stacks: Optional[List[collections.deque]] = None

    def observation_dim(self, input_dim: int) -> int:
        return input_dim * self.k

    def _ensure(self, n: int, obs: np.ndarray) -> None:
        if self._stacks is None or len(self._stacks) != n:
            self._stacks = [
                collections.deque([obs[i]] * self.k, maxlen=self.k)
                for i in range(n)]

    def __call__(self, batch: Dict[str, Any], **ctx) -> Dict[str, Any]:
        obs = np.asarray(batch["obs"])
        n = obs.shape[0]
        self._ensure(n, obs)
        dones = ctx.get("dones")
        commit = ctx.get("commit", True)
        out = np.empty((n, obs.shape[1] * self.k), obs.dtype)
        for i in range(n):
            fresh = dones is not None and dones[i]
            if commit:
                if fresh:
                    # Fresh episode: history is just the new obs.
                    self._stacks[i] = collections.deque(
                        [obs[i]] * self.k, maxlen=self.k)
                else:
                    self._stacks[i].append(obs[i])
                frames = list(self._stacks[i])
            elif fresh:
                frames = [obs[i]] * self.k
            else:
                # Peek: view with obs appended, state untouched.
                frames = list(self._stacks[i])[1:] + [obs[i]]
            out[i] = np.concatenate(frames, axis=-1)
        return {**batch, "obs": out}

    def get_state(self) -> Dict[str, Any]:
        return {}  # per-episode state is runner-local by design


class EpsilonGreedy(ConnectorV2):
    """module_to_env: override sampled actions with uniform-random ones
    with probability epsilon (reference: the EpsilonGreedy exploration
    connector). Reads epsilon / action_space_n / rng from context so the
    schedule stays owned by the algorithm."""

    def __call__(self, batch: Dict[str, Any], **ctx) -> Dict[str, Any]:
        epsilon = float(ctx.get("epsilon", 0.0) or 0.0)
        n_actions = ctx.get("action_space_n")
        rng: Optional[np.random.Generator] = ctx.get("rng")
        if epsilon <= 0.0 or n_actions is None or "actions" not in batch:
            return batch
        if rng is None:
            rng = np.random.default_rng()
        actions = np.asarray(batch["actions"])
        override = rng.random(actions.shape[0]) < epsilon
        randoms = rng.integers(n_actions, size=actions.shape[0])
        return {**batch, "actions": np.where(override, randoms, actions)}


class RunningRewardNorm(ConnectorV2):
    """learner pipeline: scale rewards by a running standard deviation
    (reference: reward-scaling connectors / MeanStdFilter). Applied to
    the sampled batch BEFORE advantage estimation, like the reference's
    learner connector ordering."""

    def __init__(self, epsilon: float = 1e-8, clip: float = 10.0):
        self.epsilon = epsilon
        self.clip = clip
        self._count = 0.0
        self._mean = 0.0
        self._m2 = 0.0

    def _update(self, rewards: np.ndarray) -> None:
        for x in np.asarray(rewards, np.float64).ravel():
            self._count += 1.0
            delta = x - self._mean
            self._mean += delta / self._count
            self._m2 += delta * (x - self._mean)

    @property
    def std(self) -> float:
        if self._count < 2:
            return 1.0
        return float(np.sqrt(self._m2 / (self._count - 1)) + self.epsilon)

    def __call__(self, batch: Dict[str, Any], **ctx) -> Dict[str, Any]:
        if "rewards" not in batch:
            return batch
        rewards = np.asarray(batch["rewards"], np.float64)
        self._update(rewards)
        scaled = np.clip(rewards / self.std, -self.clip, self.clip)
        out = dict(batch)
        out["rewards"] = scaled.astype(np.float32)
        return out

    def get_state(self) -> Dict[str, Any]:
        return {"count": self._count, "mean": self._mean, "m2": self._m2}

    def set_state(self, state: Dict[str, Any]) -> None:
        self._count = state.get("count", 0.0)
        self._mean = state.get("mean", 0.0)
        self._m2 = state.get("m2", 0.0)
