"""Device-mesh construction.

TPU-first replacement for the reference's process-group world
(python/ray/train/torch/config.py:65 `_setup_torch_process_group`): the
unit of parallelism is a `jax.sharding.Mesh` over named axes, not a flat
rank list. Axis names follow the scaling-book convention:

- ``dp``   pure data parallelism (params replicated)
- ``fsdp`` data parallelism with parameter sharding (ZeRO-3 analog —
           the reference delegates this to torch FSDP,
           python/ray/train/torch/train_loop_utils.py:184; in GSPMD it is
           just a mesh axis params are sharded over)
- ``pp``   pipeline parallelism (stage axis; see parallel/pipeline.py)
- ``tp``   tensor (megatron) parallelism
- ``sp``   sequence/context parallelism (ring attention axis)
- ``ep``   expert parallelism (MoE)

Mesh axis order matters on hardware: axes that carry the heaviest
collectives (tp, sp) must map to minor / adjacent ICI dimensions, so they
come LAST in the axis tuple (jax device order is minor-to-major locality
in reverse order of the mesh shape tuple's last axes).

Multi-slice (SURVEY §5.8 plane 3): the ``dcn`` axis is OUTERMOST — it
spans TPU slices connected by data-center network, so only the lightest
per-step collective (the data-parallel gradient all-reduce) crosses it;
fsdp/tp/sp stay inside a slice on ICI. Build such meshes with
``create_hybrid_mesh``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dcn", "dp", "pp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh: axis name -> size. -1 means 'absorb remaining'.

    Example::

        MeshSpec(dp=-1, tp=4)   # on 32 devices -> {"dp": 8, "tp": 4}
    """

    dcn: int = 1
    dp: int = 1
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wildcards = [a for a, s in sizes.items() if s == -1]
        if len(wildcards) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wildcards}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcards:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wildcards[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def mesh_shape_for(n_devices: int,
                   tp: int = 1,
                   sp: int = 1,
                   fsdp: Optional[int] = None) -> Dict[str, int]:
    """Heuristic mesh for n_devices: tp/sp as asked, rest fsdp (or dp)."""
    rest = n_devices // (tp * sp)
    if rest * tp * sp != n_devices:
        raise ValueError(f"tp*sp={tp * sp} must divide n_devices={n_devices}")
    if fsdp is None:
        return {"dp": 1, "pp": 1, "fsdp": rest, "ep": 1, "sp": sp,
                "tp": tp}
    if rest % fsdp:
        raise ValueError(f"fsdp={fsdp} must divide {rest}")
    return {"dp": rest // fsdp, "pp": 1, "fsdp": fsdp, "ep": 1, "sp": sp,
            "tp": tp}


def create_mesh(axis_sizes: Dict[str, int],
                devices: Optional[Sequence] = None,
                allow_split_physical_axes: bool = False):
    """Build a `jax.sharding.Mesh` with AXIS_ORDER-ordered named axes.

    Uses `mesh_utils.create_device_mesh` when the full device set is used so
    the logical mesh is laid out along physical ICI topology (keeps tp/sp
    collectives on-wire neighbors); falls back to reshape for subsets.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    names = tuple(a for a in AXIS_ORDER if axis_sizes.get(a, 1) >= 1)
    shape = tuple(axis_sizes.get(a, 1) for a in names)
    if math.prod(shape) != len(devices):
        raise ValueError(
            f"mesh shape {dict(zip(names, shape))} != {len(devices)} devices")
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices,
            allow_split_physical_axes=allow_split_physical_axes)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def create_hybrid_mesh(axis_sizes: Dict[str, int],
                       devices: Optional[Sequence] = None):
    """Multi-slice mesh: the outer ``dcn`` axis spans slices (DCN links);
    every other axis stays within one slice (ICI).

    On real multi-slice TPU hardware the device→mesh layout comes from
    ``mesh_utils.create_hybrid_device_mesh`` (keyed on each device's
    ``slice_index``); elsewhere (CPU worlds, single-slice ICI) devices are
    grouped contiguously so process-local devices form a slice — the
    layout the driver's virtual multi-process worlds produce.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    num_slices = int(axis_sizes.get("dcn", 1))
    names = tuple(a for a in AXIS_ORDER if axis_sizes.get(a, 1) >= 1)
    ici_names = tuple(a for a in names if a != "dcn")
    ici_shape = tuple(axis_sizes.get(a, 1) for a in ici_names)
    if num_slices * math.prod(ici_shape) != len(devices):
        raise ValueError(
            f"hybrid mesh dcn={num_slices} x ici={dict(zip(ici_names, ici_shape))} "
            f"!= {len(devices)} devices")
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if len(slice_ids) == num_slices and None not in slice_ids:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            (1, *ici_shape),
            (num_slices, *([1] * len(ici_shape))),
            devices=devices).reshape((num_slices, *ici_shape))
    else:
        dev_array = np.asarray(devices).reshape((num_slices, *ici_shape))
    return Mesh(dev_array, ("dcn", *ici_names))


def auto_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None):
    """Mesh from a MeshSpec (default: all devices on the fsdp axis)."""
    import jax

    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec(fsdp=-1)
    return create_mesh(spec.resolve(len(devices)), devices)


def local_mesh():
    """Single-process mesh over addressable devices, all on fsdp."""
    import jax

    devs = jax.local_devices()
    return create_mesh({"fsdp": len(devs)}, devs)
