"""Device-mesh construction.

TPU-first replacement for the reference's process-group world
(python/ray/train/torch/config.py:65 `_setup_torch_process_group`): the
unit of parallelism is a `jax.sharding.Mesh` over named axes, not a flat
rank list. Axis names follow the scaling-book convention:

- ``dp``   pure data parallelism (params replicated)
- ``fsdp`` data parallelism with parameter sharding (ZeRO-3 analog —
           the reference delegates this to torch FSDP,
           python/ray/train/torch/train_loop_utils.py:184; in GSPMD it is
           just a mesh axis params are sharded over)
- ``pp``   pipeline parallelism (stage axis; see parallel/pipeline.py)
- ``tp``   tensor (megatron) parallelism
- ``sp``   sequence/context parallelism (ring attention axis)
- ``ep``   expert parallelism (MoE)

Mesh axis order matters on hardware: axes that carry the heaviest
collectives (tp, sp) must map to minor / adjacent ICI dimensions, so they
come LAST in the axis tuple (jax device order is minor-to-major locality
in reverse order of the mesh shape tuple's last axes).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

AXIS_ORDER = ("dp", "pp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh: axis name -> size. -1 means 'absorb remaining'.

    Example::

        MeshSpec(dp=-1, tp=4)   # on 32 devices -> {"dp": 8, "tp": 4}
    """

    dp: int = 1
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {a: getattr(self, a) for a in AXIS_ORDER}
        wildcards = [a for a, s in sizes.items() if s == -1]
        if len(wildcards) > 1:
            raise ValueError(f"at most one -1 axis allowed, got {wildcards}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wildcards:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[wildcards[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}")
        return sizes


def mesh_shape_for(n_devices: int,
                   tp: int = 1,
                   sp: int = 1,
                   fsdp: Optional[int] = None) -> Dict[str, int]:
    """Heuristic mesh for n_devices: tp/sp as asked, rest fsdp (or dp)."""
    rest = n_devices // (tp * sp)
    if rest * tp * sp != n_devices:
        raise ValueError(f"tp*sp={tp * sp} must divide n_devices={n_devices}")
    if fsdp is None:
        return {"dp": 1, "pp": 1, "fsdp": rest, "ep": 1, "sp": sp,
                "tp": tp}
    if rest % fsdp:
        raise ValueError(f"fsdp={fsdp} must divide {rest}")
    return {"dp": rest // fsdp, "pp": 1, "fsdp": fsdp, "ep": 1, "sp": sp,
            "tp": tp}


def create_mesh(axis_sizes: Dict[str, int],
                devices: Optional[Sequence] = None,
                allow_split_physical_axes: bool = False):
    """Build a `jax.sharding.Mesh` with AXIS_ORDER-ordered named axes.

    Uses `mesh_utils.create_device_mesh` when the full device set is used so
    the logical mesh is laid out along physical ICI topology (keeps tp/sp
    collectives on-wire neighbors); falls back to reshape for subsets.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    names = tuple(a for a in AXIS_ORDER if axis_sizes.get(a, 1) >= 1)
    shape = tuple(axis_sizes.get(a, 1) for a in names)
    if math.prod(shape) != len(devices):
        raise ValueError(
            f"mesh shape {dict(zip(names, shape))} != {len(devices)} devices")
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices,
            allow_split_physical_axes=allow_split_physical_axes)
    except Exception:
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def auto_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None):
    """Mesh from a MeshSpec (default: all devices on the fsdp axis)."""
    import jax

    if devices is None:
        devices = jax.devices()
    if spec is None:
        spec = MeshSpec(fsdp=-1)
    return create_mesh(spec.resolve(len(devices)), devices)


def local_mesh():
    """Single-process mesh over addressable devices, all on fsdp."""
    import jax

    devs = jax.local_devices()
    return create_mesh({"fsdp": len(devs)}, devs)
