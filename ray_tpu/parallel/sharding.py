"""Logical-axis sharding rules.

GSPMD subsumes the reference's DDP/FSDP wrapper utilities
(python/ray/train/torch/train_loop_utils.py:158 `prepare_model`): instead
of wrapping a model, arrays carry logical axis names ("batch", "embed",
"heads", ...) and a rule table maps logical axes to mesh axes. This is the
idiom used by t5x/maxtext-style JAX trainers and is the natural TPU form.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicate)
LogicalAxisRules = Dict[str, Union[None, str, Tuple[str, ...]]]

# Default rule table for transformer LMs. Batch is split over every
# data-ish axis; embed over fsdp (ZeRO-3 analog); heads/mlp over tp;
# sequence over sp (ring attention); experts over ep.
DEFAULT_RULES: LogicalAxisRules = {
    # batch splits over every data-ish axis; "dcn" is the inter-slice
    # axis, so the only cross-slice collective is the dp grad all-reduce.
    "batch": ("dcn", "dp", "fsdp"),
    "embed": "fsdp",
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "head_dim": None,
    "qkv": "tp",
    "vocab": "tp",
    "length": "sp",
    "expert": "ep",
    "layers": None,
    "stage": "pp",
    # Paged-KV pool axes ([layers, kv_blocks, block_tokens, kv,
    # head_dim]): block ids are row-LOCAL indirection — every shard
    # must hold every block so a row's block table resolves anywhere,
    # so the pool replicates over blocks/tokens and tp-shards only
    # over kv heads (the engine's pool sharding spec picks "kv" -> tp
    # when n_kv_heads divides; see DecodeEngine paged mode).
    "kv_blocks": None,
    "block_tokens": None,
}


def prune_rules_for_mesh(rules: LogicalAxisRules, mesh: Mesh,
                         dim_sizes: Optional[Dict[str, int]] = None
                         ) -> LogicalAxisRules:
    """Restrict a rule table to what ``mesh`` can actually shard.

    For each logical axis, keep only the mesh axes that exist in the
    mesh with size > 1 AND — when ``dim_sizes`` knows the logical
    dimension — whose cumulative product divides it evenly (GSPMD
    requires even splits for donated buffers to keep their layout).
    Axes that lose every mesh axis become None (replicate).

    This is what lets one rule table serve both training and serving
    meshes: on a pure ``{"tp": 4}`` inference mesh the training axes
    (dp/fsdp/sp/...) vanish, and a model whose ``n_kv_heads`` is not
    divisible by tp degrades to replicated KV while heads/mlp/vocab
    still shard.
    """
    dim_sizes = dim_sizes or {}
    out: LogicalAxisRules = {}
    for logical, mesh_ax in rules.items():
        if mesh_ax is None:
            out[logical] = None
            continue
        axes = (mesh_ax,) if isinstance(mesh_ax, str) else tuple(mesh_ax)
        size = dim_sizes.get(logical)
        kept = []
        prod = 1
        for a in axes:
            n = dict(mesh.shape).get(a, 1)
            if n <= 1:
                continue
            if size is not None and size % (prod * n):
                continue
            kept.append(a)
            prod *= n
        out[logical] = (None if not kept
                        else kept[0] if len(kept) == 1 else tuple(kept))
    return out


def logical_to_mesh(logical_axes: Sequence[Optional[str]],
                    rules: Optional[LogicalAxisRules] = None) -> P:
    """('batch','length','embed') -> PartitionSpec(('dp','fsdp'),'sp','fsdp')."""
    rules = DEFAULT_RULES if rules is None else rules
    out = []
    used = set()
    for ax in logical_axes:
        mesh_ax = rules.get(ax) if ax is not None else None
        # A mesh axis may appear only once in a PartitionSpec; later logical
        # axes that map to an already-used mesh axis replicate instead.
        if mesh_ax is None:
            out.append(None)
            continue
        axes = (mesh_ax,) if isinstance(mesh_ax, str) else tuple(mesh_ax)
        axes = tuple(a for a in axes if a not in used)
        used.update(axes)
        if not axes:
            out.append(None)
        elif len(axes) == 1:
            out.append(axes[0])
        else:
            out.append(axes)
    return P(*out)


def spec_for(*logical_axes: Optional[str],
             rules: Optional[LogicalAxisRules] = None) -> P:
    return logical_to_mesh(logical_axes, rules)


def named_sharding(mesh: Mesh, *logical_axes: Optional[str],
                   rules: Optional[LogicalAxisRules] = None) -> NamedSharding:
    return NamedSharding(mesh, logical_to_mesh(logical_axes, rules))


def shard_pytree(tree, spec_tree, mesh: Mesh):
    """Device-put a pytree according to a matching tree of PartitionSpecs."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree, spec_tree)


def with_logical_constraint(x, *logical_axes: Optional[str],
                            rules: Optional[LogicalAxisRules] = None,
                            mesh: Optional[Mesh] = None):
    """`lax.with_sharding_constraint` via logical names; no-op outside jit
    when no mesh is available."""
    spec = logical_to_mesh(logical_axes, rules)
    if mesh is not None:
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, TypeError):
        return x
