"""ray_tpu.parallel — GSPMD mesh / sharding / collective layer.

This is the TPU-native replacement for the reference's collective plane
(python/ray/util/collective/ + torch.distributed process groups set up by
Ray Train, python/ray/train/torch/config.py:65): instead of NCCL process
groups, parallelism is expressed as a `jax.sharding.Mesh` with named axes
(dp / fsdp / tp / sp / ep) plus logical-axis sharding rules, and XLA
inserts collectives over ICI. Eager host-driven collectives (the
ray.util.collective API shape) live in `ray_tpu.collective`.
"""

from ray_tpu.parallel.mesh import (
    MeshSpec,
    create_hybrid_mesh,
    create_mesh,
    auto_mesh,
    mesh_shape_for,
    local_mesh,
)
from ray_tpu.parallel.sharding import (
    LogicalAxisRules,
    DEFAULT_RULES,
    logical_to_mesh,
    prune_rules_for_mesh,
    spec_for,
    shard_pytree,
    with_logical_constraint,
    named_sharding,
)
from ray_tpu.parallel.bootstrap import (
    initialize_distributed,
    distributed_info,
)

__all__ = [
    "MeshSpec",
    "create_hybrid_mesh",
    "create_mesh",
    "auto_mesh",
    "mesh_shape_for",
    "local_mesh",
    "LogicalAxisRules",
    "DEFAULT_RULES",
    "logical_to_mesh",
    "prune_rules_for_mesh",
    "spec_for",
    "shard_pytree",
    "with_logical_constraint",
    "named_sharding",
    "initialize_distributed",
    "distributed_info",
]
