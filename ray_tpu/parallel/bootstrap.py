"""Multi-host JAX bootstrap.

Replaces the reference's rank-0 TCP-store rendezvous for
torch.distributed (python/ray/train/torch/config.py:65-150) with
`jax.distributed.initialize`: each per-host worker actor in a gang calls
`initialize_distributed(coordinator, num_processes, process_id)`; the
train library (ray_tpu.train.JaxBackend) wires the coordinator address the
same way _TorchBackend wires MASTER_ADDR.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


@dataclasses.dataclass
class DistributedInfo:
    coordinator_address: Optional[str]
    num_processes: int
    process_id: int
    local_device_count: int
    global_device_count: int


_initialized = False


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> DistributedInfo:
    """Idempotent jax.distributed init; no-op for single-process worlds."""
    global _initialized
    import jax

    if (num_processes or 1) > 1 and not _initialized:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        _initialized = True
    return distributed_info()


def distributed_info() -> DistributedInfo:
    import jax

    return DistributedInfo(
        coordinator_address=os.environ.get("JAX_COORDINATOR_ADDRESS"),
        num_processes=jax.process_count(),
        process_id=jax.process_index(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )
