"""Pipeline parallelism — GPipe schedule inside one compiled program.

The reference has no built-in pipeline parallelism (SURVEY.md §2.4: "PP —
absent as a built-in"); its primitive is the compiled-DAG actor pipeline
with NCCL p2p channels (python/ray/dag/compiled_dag_node.py:391). The
TPU-native form is radically different: the whole pipeline is ONE jitted
SPMD program via `shard_map` over the `pp` mesh axis — each device group
holds one stage's params, activations hop stages with
`lax.ppermute` over ICI, and the microbatch schedule is a `lax.scan`
(static shapes, MXU-friendly, zero per-step driver involvement).

Bubble fraction is the GPipe (S-1)/(T+S-1); raise n_microbatches to
amortize. Backward runs through the same scan (XLA differentiates the
ppermute ring), so fwd+bwd are both pipelined.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Pytree = Any


def stack_stage_params(per_stage_params: list) -> Pytree:
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim
    (shard it over `pp` via the "stage" logical axis)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *per_stage_params)


def make_pipelined_fn(stage_fn: Callable[[Pytree, jax.Array], jax.Array],
                      mesh: Mesh,
                      n_microbatches: int,
                      axis: str = "pp"):
    """Builds pipelined(params, x) -> y.

    stage_fn(stage_params, x_microbatch) -> y_microbatch — one stage's
    compute (e.g. a scan over its layers). Activations must keep shape
    across stages (standard for decoder stacks).

    params: pytree whose leaves have a leading [n_stages] dim.
    x: [global_batch, ...] with global_batch % n_microbatches == 0.
    Returns y of the same leading shape, replicated across `pp`.
    """
    n_stages = mesh.shape[axis]

    def _program(params, x):
        # Inside shard_map: params leaves have leading dim 1 (this
        # stage's block); x is replicated.
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage_id = jax.lax.axis_index(axis)
        gb = x.shape[0]
        mb = gb // n_microbatches
        micro = x.reshape((n_microbatches, mb) + x.shape[1:])
        n_ticks = n_microbatches + n_stages - 1

        state = jnp.zeros_like(micro[0])
        outputs = jnp.zeros_like(micro)

        def tick(carry, t):
            state, outputs = carry
            in_idx = jnp.clip(t, 0, n_microbatches - 1)
            x_in = jnp.where(stage_id == 0, micro[in_idx], state)
            y = stage_fn(params, x_in)
            out_idx = t - (n_stages - 1)
            write = jnp.logical_and(stage_id == n_stages - 1, out_idx >= 0)
            safe_idx = jnp.clip(out_idx, 0, n_microbatches - 1)
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(write, y,
                          jax.lax.dynamic_index_in_dim(
                              outputs, safe_idx, keepdims=False)),
                safe_idx, axis=0)
            # Activations hop to the next stage over ICI.
            state = jax.lax.ppermute(
                y, axis, [(i, i + 1) for i in range(n_stages - 1)])
            return (state, outputs), None

        (state, outputs), _ = jax.lax.scan(
            tick, (state, outputs), jnp.arange(n_ticks))
        # Only the last stage wrote outputs; psum broadcasts them so the
        # result is replicated over pp (other stages contributed zeros).
        outputs = jax.lax.psum(
            jnp.where(stage_id == n_stages - 1, outputs,
                      jnp.zeros_like(outputs)), axis)
        return outputs.reshape((gb,) + outputs.shape[2:])

    def pipelined(params, x):
        from jax import shard_map

        in_specs = (
            jax.tree_util.tree_map(lambda _: P(axis), params),
            P(),
        )
        fn = shard_map(_program, mesh=mesh, in_specs=in_specs,
                       out_specs=P(), check_vma=False)
        return fn(params, x)

    return pipelined
