"""Distributed progress bars.

Reference: python/ray/experimental/tqdm_ray.py — a tqdm-compatible bar
whose updates flow from workers to the driver instead of fighting over
the worker's (invisible) terminal. TPU-native simplification: updates
ride the EXISTING worker-log streaming plane (worker stdout → raylet
log monitor → GCS pubsub → driver console), as throttled single-line
progress records — no extra channel, and bars from any number of
workers interleave as ordinary prefixed driver lines.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Any, Iterable, Optional

# At most one line per bar per this interval (plus first/last update):
# progress is chatty, the log plane is shared.
_MIN_INTERVAL_S = 0.5


class tqdm:  # noqa: N801  (tqdm-compatible name)
    """Subset-compatible with tqdm.tqdm: iterable wrapping, update(),
    set_description(), close(); total/desc/position kwargs accepted."""

    def __init__(self, iterable: Optional[Iterable] = None, *,
                 desc: str = "", total: Optional[int] = None,
                 position: int = 0, **_ignored: Any):
        self._iterable = iterable
        self.desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)  # type: ignore[arg-type]
            except TypeError:
                total = None
        self.total = total
        self.n = 0
        self._last_print = 0.0
        self._closed = False
        self._emit(force=True)

    # ---- tqdm API subset ----
    def update(self, n: int = 1) -> None:
        self.n += n
        self._emit()

    def set_description(self, desc: str, refresh: bool = True) -> None:
        self.desc = desc
        if refresh:
            self._emit()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._emit(force=True)

    def __iter__(self):
        if self._iterable is None:
            raise TypeError("tqdm bar created without an iterable")
        try:
            for item in self._iterable:
                yield item
                self.update(1)
        finally:
            self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # ---- emission ----
    def _emit(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_print < _MIN_INTERVAL_S:
            return
        self._last_print = now
        total = f"/{self.total}" if self.total is not None else ""
        desc = f"{self.desc}: " if self.desc else ""
        state = " done" if self._closed else ""
        # Plain stdout: on a worker this streams to the driver console
        # via the log monitor; on the driver it prints directly.
        print(f"[tqdm_ray pid={os.getpid()}] {desc}{self.n}{total}{state}",
              flush=True)


def safe_print(*args: Any, **kwargs: Any) -> None:
    """Reference-compat shim (tqdm_ray.safe_print): plain print — bars
    here are ordinary log lines, so prints never corrupt them."""
    kwargs.setdefault("file", sys.stdout)
    print(*args, **kwargs)
