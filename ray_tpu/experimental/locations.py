"""Object locations API.

Reference: python/ray/experimental/locations.py
(``ray.experimental.get_object_locations`` — per-ref node ids + size
from the owner's object directory). Here the GCS object directory
(gcs_server handle_get_object_locations) is the source of truth; the
local shm store supplies the size when the object is resident on this
node, and spilled objects report their external-storage URL.
"""

from __future__ import annotations

from typing import Any, Dict, List


def get_object_locations(obj_refs: List[Any],
                         timeout_ms: int = -1) -> Dict[Any, dict]:
    """{ref: {"node_ids": [hex], "object_size": int|None,
    "spilled_url": str|None, "did_spill": bool}} for each ref.

    One batched GCS round-trip regardless of len(obj_refs);
    timeout_ms < 0 means the default RPC timeout."""
    from ray_tpu._private.worker import global_worker

    worker = global_worker()
    oids = [ref.id.binary() if hasattr(ref.id, "binary") else ref.id
            for ref in obj_refs]
    kwargs = {}
    if timeout_ms >= 0:
        kwargs["timeout"] = max(timeout_ms / 1000.0, 0.001)
    reply = worker.gcs_call("get_object_locations",
                            {"object_ids": oids}, **kwargs)
    plasma = getattr(worker.core, "plasma", None)  # None in client mode
    out: Dict[Any, dict] = {}
    for ref, info in zip(obj_refs, reply["batch"]):
        nodes = [n["node_id"].hex() if isinstance(n["node_id"], bytes)
                 else str(n["node_id"]) for n in info.get("nodes", [])]
        size = plasma.object_size(ref.id) if plasma is not None else None
        spilled = info.get("spilled_url")
        out[ref] = {
            "node_ids": nodes,
            "object_size": size,
            "spilled_url": spilled,
            "did_spill": spilled is not None,
        }
    return out
