"""ray_tpu.experimental — misc APIs mirroring python/ray/experimental/:
locations (get_object_locations), tqdm_ray (distributed progress bars),
channel (compiled-graph channels)."""

from ray_tpu.experimental.locations import get_object_locations

__all__ = ["get_object_locations"]
