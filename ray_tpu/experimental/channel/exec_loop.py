"""Worker-side compiled-DAG execution loop.

Reference: python/ray/dag/compiled_dag_node.py (do_exec_tasks — the
per-actor loop that a compiled DAG installs on each participating actor).
The loop reads its input channels, runs the actor's bound methods, and
writes results to its output channels — no driver involvement per step.

The loop runs inside the actor's executor thread (dispatched like any
actor task); channel reads/writes block in native code with the GIL
released, so the worker's io loop stays live for health checks and
teardown RPCs.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

from ray_tpu.core import serialization as ser
from ray_tpu.experimental.channel.shm_channel import Channel, ChannelClosed

logger = logging.getLogger(__name__)


class _ErrorEnvelope:
    """Marks a value as an upstream error travelling through channels."""

    __slots__ = ("error",)

    def __init__(self, error: Exception):
        self.error = error

    def __reduce__(self):
        return (type(self), (self.error,))


def run_dag_loop(instance: Any, plan: Dict) -> int:
    """Execute the compiled plan until the input channels close.

    plan = {
      "in_chans":  [(path, reader_id), ...],
      "steps": [{"method": str,
                 "args": [argspec, ...],
                 "kwargs": {name: argspec},
                 "outs": [out_chan_index, ...]}, ...],
      "out_chans": [path, ...],
    }
    argspec = ("chan", in_index) | ("const", pickled) | ("local", step_idx)

    Returns the number of completed iterations.
    """
    in_chans = [Channel(path, reader_id)
                for path, reader_id in plan["in_chans"]]
    out_chans = [Channel(path) for path in plan["out_chans"]]
    steps = plan["steps"]

    # Reads and writes are interleaved in plan order: each input channel is
    # read just before its earliest consuming step, and each step's outputs
    # are written immediately after it runs.  This keeps actor-revisit DAGs
    # (A.f1 -> B.g -> A.f2) live: A publishes f1's result before blocking on
    # the channel that B feeds.
    first_use: Dict[int, int] = {}
    for si, step in enumerate(steps):
        for spec in list(step["args"]) + list(step["kwargs"].values()):
            if spec[0] == "chan" and spec[1] not in first_use:
                first_use[spec[1]] = si
    reads_at: Dict[int, List[int]] = {}
    for ci in range(len(in_chans)):
        reads_at.setdefault(first_use.get(ci, 0), []).append(ci)

    consts = {}
    iterations = 0
    try:
        while True:
            inputs: List[Any] = [None] * len(in_chans)
            local_results: List[Any] = []
            error = None

            def resolve(spec):
                kind, idx = spec
                if kind == "chan":
                    return inputs[idx]
                if kind == "local":
                    return local_results[idx]
                if idx not in consts:
                    consts[idx] = ser.loads(plan["consts"][idx])
                return consts[idx]

            for si, step in enumerate(steps):
                for ci in reads_at.get(si, ()):
                    inputs[ci] = in_chans[ci].read()
                    if error is None and isinstance(inputs[ci],
                                                    _ErrorEnvelope):
                        error = inputs[ci]
                if error is not None:
                    result = error
                else:
                    try:
                        args = [resolve(a) for a in step["args"]]
                        kwargs = {k: resolve(v)
                                  for k, v in step["kwargs"].items()}
                        result = getattr(instance, step["method"])(*args,
                                                                   **kwargs)
                    except Exception as e:  # travels on, loop lives
                        import traceback

                        error = _ErrorEnvelope(ser.RayTaskError(
                            step["method"], traceback.format_exc(), repr(e),
                            cause=e if _picklable(e) else None))
                        result = error
                local_results.append(result)
                for out_idx in step["outs"]:
                    out_chans[out_idx].write(result)
            iterations += 1
    except ChannelClosed:
        return iterations
    finally:
        for c in out_chans:
            c.close()
        for c in in_chans + out_chans:
            c.release()


def _picklable(e: Exception) -> bool:
    import pickle

    try:
        pickle.dumps(e)
        return True
    except Exception:
        return False
