"""Worker-side compiled-DAG execution loop.

Reference: python/ray/dag/compiled_dag_node.py (do_exec_tasks — the
per-actor loop that a compiled DAG installs on each participating actor).
The loop reads its input channels, runs the actor's bound methods, and
writes results to its output channels — no driver involvement per step.

The loop runs inside the actor's executor thread (dispatched like any
actor task); channel reads/writes block in native code with the GIL
released, so the worker's io loop stays live for health checks and
teardown RPCs.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List

from ray_tpu.core import serialization as ser
from ray_tpu.experimental.channel.shm_channel import Channel, ChannelClosed

logger = logging.getLogger(__name__)


class _ErrorEnvelope:
    """Marks a value as an upstream error travelling through channels."""

    __slots__ = ("error",)

    def __init__(self, error: Exception):
        self.error = error

    def __reduce__(self):
        return (type(self), (self.error,))


def run_dag_loop(instance: Any, plan: Dict) -> int:
    """Execute the compiled plan until the input channels close.

    plan = {
      "in_chans":  [(path, reader_id), ...],
      "steps": [{"method": str,
                 "args": [argspec, ...],
                 "kwargs": {name: argspec},
                 "outs": [out_chan_index, ...]}, ...],
      "out_chans": [path, ...],
    }
    argspec = ("chan", in_index) | ("const", pickled) | ("local", step_idx)

    Returns the number of completed iterations.
    """
    in_chans = [Channel(path, reader_id)
                for path, reader_id in plan["in_chans"]]
    out_chans = [Channel(path) for path in plan["out_chans"]]
    steps = plan["steps"]
    consts = {}
    iterations = 0
    try:
        while True:
            try:
                inputs = [c.read() for c in in_chans]
            except ChannelClosed:
                return iterations

            def resolve(spec):
                kind, idx = spec
                if kind == "chan":
                    return inputs[idx]
                if kind == "local":
                    return local_results[idx]
                if idx not in consts:
                    consts[idx] = ser.loads(plan["consts"][idx])
                return consts[idx]

            local_results: List[Any] = []
            error = next((v for v in inputs
                          if isinstance(v, _ErrorEnvelope)), None)
            for step in steps:
                if error is not None:
                    local_results.append(error)
                    continue
                try:
                    args = [resolve(a) for a in step["args"]]
                    kwargs = {k: resolve(v)
                              for k, v in step["kwargs"].items()}
                    result = getattr(instance, step["method"])(*args,
                                                               **kwargs)
                except Exception as e:  # travels to consumers, loop lives on
                    import traceback

                    error = _ErrorEnvelope(ser.RayTaskError(
                        step["method"], traceback.format_exc(), repr(e),
                        cause=e if _picklable(e) else None))
                    result = error
                local_results.append(result)
            for step, result in zip(steps, local_results):
                for out_idx in step["outs"]:
                    out_chans[out_idx].write(result)
            iterations += 1
    except ChannelClosed:
        return iterations
    finally:
        for c in out_chans:
            c.close()
        for c in in_chans + out_chans:
            c.release()


def _picklable(e: Exception) -> bool:
    import pickle

    try:
        pickle.dumps(e)
        return True
    except Exception:
        return False
