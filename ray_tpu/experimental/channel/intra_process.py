"""In-process channel: same API as the shm channel, queue-backed.

Reference: python/ray/experimental/channel/intra_process_channel.py — used
when producer and consumer share a process (e.g. driver self-edges, tests).
"""

from __future__ import annotations

import queue
from typing import Any, Optional

from ray_tpu.experimental.channel.shm_channel import (ChannelClosed,
                                                      ChannelTimeout)

_CLOSED = object()


class IntraProcessChannel:
    def __init__(self, maxsize: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=maxsize)
        self._closed = False

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        if self._closed:
            raise ChannelClosed("channel is closed")
        try:
            self._q.put(value, timeout=timeout)
        except queue.Full:
            raise ChannelTimeout("write timed out") from None

    def read(self, timeout: Optional[float] = None) -> Any:
        try:
            value = self._q.get(timeout=timeout)
        except queue.Empty:
            if self._closed:
                raise ChannelClosed("channel is closed") from None
            raise ChannelTimeout("read timed out") from None
        if value is _CLOSED:
            self._closed = True
            raise ChannelClosed("channel is closed")
        return value

    def close(self) -> None:
        self._closed = True
        try:
            self._q.put_nowait(_CLOSED)
        except queue.Full:
            pass

    def destroy(self) -> None:
        self.close()

    def release(self) -> None:
        pass
