"""Mutable shared-memory channel (ctypes client).

Reference: python/ray/experimental/channel/shared_memory_channel.py:147
(Channel over mutable plasma objects; native side
src/ray/core_worker/experimental_mutable_object_manager.h). Redesign: the
channel is its own double-buffered mmap file (_native/mutable_channel.cpp)
— no store daemon, no object IDs; writer and readers map the same file and
synchronize on an in-segment robust mutex/condvar. Blocking calls release
the GIL (plain ctypes), so readers/writers block their own thread without
touching any event loop — a compiled-DAG step does zero RPCs.
"""

from __future__ import annotations

import ctypes
import os
import uuid
from typing import Any, Optional

from ray_tpu.core import serialization as ser

_OK = 0
_ERR_TIMEOUT = -4
_ERR_INVALID = -5
_ERR_CLOSED = -8
_ERR_TOO_LARGE = -9

_lib = None


class ChannelClosed(Exception):
    pass


class ChannelTimeout(TimeoutError):
    pass


def _load():
    global _lib
    if _lib is not None:
        return _lib
    from ray_tpu._native.build import load_lib

    lib = load_lib("ray_tpu_channel")
    lib.chan_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                ctypes.c_uint32, ctypes.c_uint32]
    lib.chan_create.restype = ctypes.c_int
    lib.chan_open.argtypes = [ctypes.c_char_p]
    lib.chan_open.restype = ctypes.c_void_p
    lib.chan_close_handle.argtypes = [ctypes.c_void_p]
    lib.chan_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint64, ctypes.c_long]
    lib.chan_write.restype = ctypes.c_int
    lib.chan_read_acquire.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_long]
    lib.chan_read_acquire.restype = ctypes.c_int
    lib.chan_read_release.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.chan_read_release.restype = ctypes.c_int
    lib.chan_close.argtypes = [ctypes.c_void_p]
    lib.chan_close.restype = ctypes.c_int
    lib.chan_stats.argtypes = [ctypes.c_void_p,
                               ctypes.POINTER(ctypes.c_uint64),
                               ctypes.POINTER(ctypes.c_uint64),
                               ctypes.POINTER(ctypes.c_uint32)]
    lib.chan_stats.restype = ctypes.c_int
    _lib = lib
    return lib


def _to_ms(timeout: Optional[float]) -> int:
    return -1 if timeout is None else max(0, int(timeout * 1000))


class Channel:
    """One single-producer, N-reader mutable channel.

    ``write(value)`` publishes; each reader (identified by ``reader_id``)
    consumes values strictly in order via ``read()`` (copy + deserialize)
    or ``begin_read()``/``end_read()`` (zero-copy window).
    """

    DEFAULT_CAPACITY = 16 << 20

    def __init__(self, path: str, reader_id: int = 0):
        self.path = path
        self.reader_id = reader_id
        self._h = _load().chan_open(path.encode())
        if not self._h:
            raise ValueError(f"cannot open channel at {path}")
        self._reading = False

    @classmethod
    def create(cls, n_readers: int = 1,
               capacity: int = DEFAULT_CAPACITY,
               directory: Optional[str] = None,
               n_slots: int = 8) -> str:
        """Allocate a new channel segment; returns its path (shippable to
        other processes on this node — open with Channel(path, reader_id)).
        ``n_slots`` is the ring depth: how many published-but-unread values
        the channel buffers before writers block (2..64)."""
        directory = directory or ("/dev/shm" if os.path.isdir("/dev/shm")
                                  else "/tmp")
        path = os.path.join(directory, f"ray_tpu_chan_{uuid.uuid4().hex}")
        rc = _load().chan_create(path.encode(), capacity, n_readers,
                                 n_slots)
        if rc != _OK:
            raise RuntimeError(f"chan_create failed rc={rc}")
        return path

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        data = ser.dumps(value)
        self.write_bytes(data, timeout)

    def write_bytes(self, data: bytes, timeout: Optional[float] = None) -> None:
        rc = _load().chan_write(self._h, data, len(data), _to_ms(timeout))
        if rc == _OK:
            return
        if rc == _ERR_CLOSED:
            raise ChannelClosed(f"channel {self.path} is closed")
        if rc == _ERR_TIMEOUT:
            raise ChannelTimeout(f"write timed out on {self.path}")
        if rc == _ERR_TOO_LARGE:
            raise ValueError(
                f"value of {len(data)} bytes exceeds channel capacity")
        raise RuntimeError(f"chan_write rc={rc}")

    def begin_read(self, timeout: Optional[float] = None) -> memoryview:
        """Zero-copy read window; MUST be paired with end_read()."""
        if self._reading:
            raise RuntimeError("begin_read() without end_read()")
        ptr = ctypes.POINTER(ctypes.c_uint8)()
        length = ctypes.c_uint64()
        rc = _load().chan_read_acquire(self._h, self.reader_id,
                                       ctypes.byref(ptr),
                                       ctypes.byref(length),
                                       _to_ms(timeout))
        if rc == _ERR_CLOSED:
            raise ChannelClosed(f"channel {self.path} is closed")
        if rc == _ERR_TIMEOUT:
            raise ChannelTimeout(f"read timed out on {self.path}")
        if rc != _OK:
            raise RuntimeError(f"chan_read_acquire rc={rc}")
        self._reading = True
        return memoryview((ctypes.c_uint8 * length.value).from_address(
            ctypes.addressof(ptr.contents))).cast("B")

    def end_read(self) -> None:
        if not self._reading:
            return
        self._reading = False
        _load().chan_read_release(self._h, self.reader_id)

    def read(self, timeout: Optional[float] = None) -> Any:
        """Read the next value (copies out of the window, then releases —
        safe default; use begin_read for zero-copy)."""
        view = self.begin_read(timeout)
        try:
            data = bytes(view)
        finally:
            self.end_read()
        return ser.loads(data)

    def close(self) -> None:
        """Mark the channel closed (wakes all blocked peers)."""
        if self._h:
            _load().chan_close(self._h)

    def stats(self) -> dict:
        w = ctypes.c_uint64()
        m = ctypes.c_uint64()
        c = ctypes.c_uint32()
        _load().chan_stats(self._h, ctypes.byref(w), ctypes.byref(m),
                           ctypes.byref(c))
        return {"write_seq": w.value, "min_read_seq": m.value,
                "closed": bool(c.value)}

    def destroy(self) -> None:
        """Close, release the mapping, and unlink the segment file."""
        self.close()
        self.release()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def release(self) -> None:
        if self._h:
            _load().chan_close_handle(self._h)
            self._h = None

    def __reduce__(self):
        return (type(self), (self.path, self.reader_id))
