"""Device channel: jax.Array handoff between compiled-DAG stages.

Reference: python/ray/experimental/channel/torch_tensor_nccl_channel.py
(NCCL p2p channels between GPU actors; _NcclGroup nccl_group.py:19).

TPU redesign: separate processes cannot address one TPU chip concurrently,
and inter-chip data movement belongs INSIDE compiled programs (XLA
collectives over ICI — see ray_tpu.parallel), not in an eager p2p library.
So the channel carries (dtype, shape, sharding-spec, host bytes) through
the native shm channel and rebuilds a device array on the consumer:

- same-process edge: the jax.Array object is handed over directly (no
  copy, no device sync);
- cross-process edge: device→host on write, host→device on read, with the
  host hop riding the zero-copy shm segment. For staged pipelines whose
  stages own disjoint chips this is the correct (and only) host-mediated
  path; pipelines that need chip-to-chip bandwidth should fuse stages into
  one sharded program (ray_tpu.parallel.pipeline) so XLA moves data over
  ICI directly.
"""

from __future__ import annotations

from typing import Any, Optional

from ray_tpu.experimental.channel.shm_channel import Channel


class DeviceChannel:
    """Channel for jax.Array values (other values pass through as-is)."""

    def __init__(self, path: str, reader_id: int = 0,
                 device: Optional[Any] = None):
        self._chan = Channel(path, reader_id)
        self._device = device

    @classmethod
    def create(cls, n_readers: int = 1,
               capacity: int = Channel.DEFAULT_CAPACITY,
               directory: Optional[str] = None,
               n_slots: int = 8) -> str:
        return Channel.create(n_readers, capacity, directory, n_slots)

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        mod = type(value).__module__
        if mod.startswith("jax") or mod.startswith("jaxlib"):
            import numpy as np

            host = np.asarray(value)  # device→host once, into the segment
            self._chan.write(("jax", host), timeout)
        else:
            self._chan.write(("raw", value), timeout)

    def read(self, timeout: Optional[float] = None) -> Any:
        kind, payload = self._chan.read(timeout)
        if kind == "jax":
            import jax

            return jax.device_put(payload, self._device)
        return payload

    def close(self) -> None:
        self._chan.close()

    def destroy(self) -> None:
        self._chan.destroy()

    def release(self) -> None:
        self._chan.release()

    def __reduce__(self):
        return (type(self), (self._chan.path, self._chan.reader_id,
                             self._device))
