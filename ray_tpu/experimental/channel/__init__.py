"""Channels: the compiled-DAG data plane.

Reference: python/ray/experimental/channel/ — mutable shared-memory
channels (shared_memory_channel.py:147) and device p2p channels
(torch_tensor_nccl_channel.py). Here: a native double-buffered shm
channel (_native/mutable_channel.cpp) for host data, an in-process
channel for same-process edges, and a device channel interface for
jax.Array handoff.
"""

from ray_tpu.experimental.channel.shm_channel import (Channel,
                                                      ChannelClosed,
                                                      ChannelTimeout)
from ray_tpu.experimental.channel.intra_process import IntraProcessChannel
from ray_tpu.experimental.channel.device_channel import DeviceChannel

__all__ = [
    "Channel",
    "ChannelClosed",
    "ChannelTimeout",
    "IntraProcessChannel",
    "DeviceChannel",
]
