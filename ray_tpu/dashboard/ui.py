"""Dashboard single-page UI (reference: python/ray/dashboard/client/ —
a React app there; a dependency-free vanilla-JS page here, served by the
dashboard head over the same JSON endpoints)."""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 0; background: #f6f7f9; color: #1c2733; }
  header { background: #1c2733; color: #fff; padding: 10px 20px;
           display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 18px; margin: 0; }
  header .sub { color: #9fb0c0; font-size: 12px; }
  nav { display: flex; gap: 4px; padding: 8px 16px 0; }
  nav button { border: 0; background: #e2e6ea; padding: 8px 14px;
               border-radius: 6px 6px 0 0; cursor: pointer; font-size: 13px; }
  nav button.active { background: #fff; font-weight: 600; }
  main { background: #fff; margin: 0 16px 16px; padding: 16px;
         border-radius: 0 6px 6px 6px; min-height: 400px; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th, td { text-align: left; padding: 6px 10px;
           border-bottom: 1px solid #e7ebef; }
  th { color: #5a6b7b; font-weight: 600; font-size: 12px;
       text-transform: uppercase; }
  .pill { padding: 2px 8px; border-radius: 10px; font-size: 12px; }
  .ALIVE, .RUNNING, .SUCCEEDED { background: #e2f5e8; color: #176639; }
  .DEAD, .FAILED, .ERROR { background: #fdeaea; color: #8f2020; }
  .PENDING, .RESTARTING, .STOPPED { background: #fff4de; color: #7a5b12; }
  .cards { display: flex; gap: 12px; flex-wrap: wrap; margin-bottom: 14px; }
  .card { background: #f2f5f8; border-radius: 8px; padding: 12px 18px;
          min-width: 140px; }
  .card .v { font-size: 22px; font-weight: 700; }
  .card .k { font-size: 12px; color: #5a6b7b; }
  #err { color: #8f2020; font-size: 12px; padding: 4px 16px; }
</style>
</head>
<body>
<header><h1>ray_tpu</h1>
  <span class="sub">cluster dashboard &middot;
    refreshed <span id="ts">never</span></span></header>
<nav>
  <button data-tab="overview" class="active">Overview</button>
  <button data-tab="nodes">Nodes</button>
  <button data-tab="actors">Actors</button>
  <button data-tab="jobs">Jobs</button>
  <button data-tab="tasks">Tasks</button>
</nav>
<div id="err"></div>
<main id="content">loading…</main>
<script>
let tab = 'overview';
const $ = (s) => document.querySelector(s);
const esc = (s) => String(s).replace(/[&<>"']/g, (c) => ({
  '&': '&amp;', '<': '&lt;', '>': '&gt;', '"': '&quot;', "'": '&#39;'
}[c]));
const fmtBytes = (b) => {
  if (!b && b !== 0) return '';
  const u = ['B','KiB','MiB','GiB','TiB']; let i = 0;
  while (b >= 1024 && i < u.length - 1) { b /= 1024; i++; }
  return b.toFixed(i ? 1 : 0) + ' ' + u[i];
};
const PILL_OK = /^[A-Z_]+$/;
const pill = (s) => PILL_OK.test(String(s)) ?
  `<span class="pill ${s}">${s}</span>` : esc(s);
// Cell renderers returning plain values are HTML-escaped; only the
// pill() helper (validated charset) emits markup.
const cell = (v) => (typeof v === 'string' && v.startsWith('<span class="pill '))
  ? v : esc(v ?? '');
const table = (cols, rows) =>
  `<table><tr>${cols.map(c => `<th>${esc(c[0])}</th>`).join('')}</tr>` +
  rows.map(r => `<tr>${cols.map(c => `<td>${cell(c[1](r))}</td>`)
    .join('')}</tr>`).join('') + '</table>';
async function j(url) { const r = await fetch(url);
  if (!r.ok) throw new Error(url + ': ' + r.status); return r.json(); }

const views = {
  async overview() {
    const [cs, stats] = await Promise.all(
      [j('/api/cluster_status'), j('/api/node_stats')]);
    const res = cs.resources || {};
    const cards = [
      ['nodes alive', `${cs.nodes_alive}/${cs.nodes_total}`],
      ['CPUs', `${(res.available||{}).CPU ?? '?'} / ${(res.total||{}).CPU ?? '?'}`],
      ['TPUs', `${(res.available||{}).TPU ?? 0} / ${(res.total||{}).TPU ?? 0}`],
    ];
    let html = '<div class="cards">' + cards.map(([k, v]) =>
      `<div class="card"><div class="v">${esc(v)}</div>` +
      `<div class="k">${esc(k)}</div></div>`).join('') + '</div>';
    html += '<h3>Per-node hardware</h3>' + table([
      ['node', r => (r.node_id || '').slice(0, 8)],
      ['host', r => r.hostname],
      ['cpu %', r => r['node.cpu_percent']?.toFixed(1)],
      ['mem avail', r => fmtBytes(r['node.mem_available_bytes'])],
      ['store used', r => fmtBytes(r['node.object_store_used_bytes'])],
      ['store cap', r => fmtBytes(r['node.object_store_capacity_bytes'])],
      ['tpu free/total', r => r['node.tpu_total'] ?
        `${r['node.tpu_available']}/${r['node.tpu_total']}` : '-'],
    ], stats);
    return html;
  },
  async nodes() {
    const nodes = await j('/api/nodes');
    return table([
      ['node', r => (r.node_id || '').slice(0, 8)],
      ['state', r => pill(r.state)],
      ['address', r => r.address],
      ['slice', r => r.slice_id || '-'],
      ['cpu avail', r => (r.resources_available || {}).CPU],
      ['tpu avail', r => (r.resources_available || {}).TPU ?? '-'],
    ], nodes);
  },
  async actors() {
    const actors = await j('/api/actors');
    return table([
      ['actor', r => (r.actor_id || '').slice(0, 8)],
      ['class', r => r.class_name],
      ['name', r => r.name || ''],
      ['state', r => pill(r.state)],
      ['restarts', r => r.num_restarts],
      ['node', r => (r.node_id || '').slice(0, 8)],
    ], actors);
  },
  async jobs() {
    const jobs = await j('/api/jobs');
    return table([
      ['job', r => r.submission_id || r.job_id],
      ['status', r => pill(r.status || r.state)],
      ['entrypoint', r => r.entrypoint || ''],
    ], jobs);
  },
  async tasks() {
    const summary = await j('/api/tasks/summary');
    const rows = Object.entries(summary).map(([name, states]) =>
      ({name, ...states}));
    return table([
      ['task', r => r.name],
      ['pending', r => r.PENDING ?? 0],
      ['running', r => r.RUNNING ?? 0],
      ['finished', r => r.FINISHED ?? 0],
      ['failed', r => r.FAILED ?? 0],
    ], rows);
  },
};

async function refresh() {
  try {
    $('#content').innerHTML = await views[tab]();
    $('#ts').textContent = new Date().toLocaleTimeString();
    $('#err').textContent = '';
  } catch (e) { $('#err').textContent = String(e); }
}
document.querySelectorAll('nav button').forEach(b =>
  b.addEventListener('click', () => {
    document.querySelectorAll('nav button').forEach(x =>
      x.classList.remove('active'));
    b.classList.add('active');
    tab = b.dataset.tab;
    refresh();
  }));
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""
