"""Dashboard single-page UI (reference: python/ray/dashboard/client/ —
a React app there; a dependency-free vanilla-JS page here, served by the
dashboard head over the same JSON endpoints).

Coverage mirrors the reference app's modules: overview cards + per-node
hardware (reporter), node/actor/PG/job/task tables with row drill-down
detail panels, an in-browser task timeline rendered from the chrome-trace
endpoint (modules/metrics + timeline) with wheel-zoom + drag-pan, and
push-style in-browser log following over the long-poll
/api/logs/stream endpoint (modules/log). Everything the CLI can show is
reachable here.
"""

INDEX_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>ray_tpu dashboard</title>
<style>
  body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif;
         margin: 0; background: #f6f7f9; color: #1c2733; }
  header { background: #1c2733; color: #fff; padding: 10px 20px;
           display: flex; align-items: baseline; gap: 16px; }
  header h1 { font-size: 18px; margin: 0; }
  header .sub { color: #9fb0c0; font-size: 12px; }
  nav { display: flex; gap: 4px; padding: 8px 16px 0; flex-wrap: wrap; }
  nav button { border: 0; background: #e2e6ea; padding: 8px 14px;
               border-radius: 6px 6px 0 0; cursor: pointer; font-size: 13px; }
  nav button.active { background: #fff; font-weight: 600; }
  main { background: #fff; margin: 0 16px 16px; padding: 16px;
         border-radius: 0 6px 6px 6px; min-height: 400px; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th, td { text-align: left; padding: 6px 10px;
           border-bottom: 1px solid #e7ebef; }
  th { color: #5a6b7b; font-weight: 600; font-size: 12px;
       text-transform: uppercase; }
  tr.click { cursor: pointer; }
  tr.click:hover { background: #f2f6fa; }
  .pill { padding: 2px 8px; border-radius: 10px; font-size: 12px; }
  .ALIVE, .RUNNING, .SUCCEEDED, .CREATED, .FINISHED
    { background: #e2f5e8; color: #176639; }
  .DEAD, .FAILED, .ERROR { background: #fdeaea; color: #8f2020; }
  .PENDING, .RESTARTING, .STOPPED, .RESCHEDULING
    { background: #fff4de; color: #7a5b12; }
  .cards { display: flex; gap: 12px; flex-wrap: wrap; margin-bottom: 14px; }
  .card { background: #f2f5f8; border-radius: 8px; padding: 12px 18px;
          min-width: 140px; }
  .card .v { font-size: 22px; font-weight: 700; }
  .card .k { font-size: 12px; color: #5a6b7b; }
  #err { color: #8f2020; font-size: 12px; padding: 4px 16px; }
  .detail { background: #f8fafc; border: 1px solid #e2e8f0;
            border-radius: 8px; padding: 12px 16px; margin-bottom: 12px; }
  .detail h3 { margin: 0 0 8px; font-size: 14px; }
  .detail table { width: auto; }
  .detail td { border: 0; padding: 2px 14px 2px 0; font-size: 13px;
               vertical-align: top; }
  .detail td:first-child { color: #5a6b7b; white-space: nowrap; }
  .detail .close { float: right; cursor: pointer; color: #5a6b7b; }
  pre.log { background: #101418; color: #d7e1ea; padding: 12px;
            border-radius: 8px; font-size: 12px; overflow-x: auto;
            max-height: 480px; overflow-y: auto; white-space: pre-wrap; }
  /* timeline */
  .tl-wrap { overflow-x: auto; border: 1px solid #e2e8f0;
             border-radius: 8px; }
  .tl { position: relative; min-height: 60px; }
  .tl-lane-label { position: sticky; left: 0; width: 110px;
                   font-size: 11px; color: #5a6b7b; padding: 2px 6px;
                   background: #f8fafc; border-right: 1px solid #e2e8f0;
                   overflow: hidden; white-space: nowrap; }
  .tl-row { display: flex; border-bottom: 1px solid #eef2f6; }
  .tl-track { position: relative; height: 22px; flex: 1; }
  .tl-bar { position: absolute; top: 3px; height: 16px; border-radius: 3px;
            min-width: 2px; opacity: .9; }
  .tl-axis { font-size: 11px; color: #5a6b7b; padding: 4px 0 2px 116px; }
</style>
</head>
<body>
<header><h1>ray_tpu</h1>
  <span class="sub">cluster dashboard &middot;
    refreshed <span id="ts">never</span></span></header>
<nav>
  <button data-tab="overview" class="active">Overview</button>
  <button data-tab="nodes">Nodes</button>
  <button data-tab="actors">Actors</button>
  <button data-tab="pgs">Placement groups</button>
  <button data-tab="jobs">Jobs</button>
  <button data-tab="tasks">Tasks</button>
  <button data-tab="timeline">Timeline</button>
  <button data-tab="serve">Serve</button>
  <button data-tab="metrics">Metrics</button>
  <button data-tab="events">Events</button>
  <button data-tab="logs">Logs</button>
</nav>
<div id="err"></div>
<main id="content">loading…</main>
<script>
let tab = 'overview';
let detail = null;    // currently-open drill-down row
let logFile = null;   // currently-tailed log file
const $ = (s) => document.querySelector(s);
const esc = (s) => String(s).replace(/[&<>"']/g, (c) => ({
  '&': '&amp;', '<': '&lt;', '>': '&gt;', '"': '&quot;', "'": '&#39;'
}[c]));
const fmtBytes = (b) => {
  if (!b && b !== 0) return '';
  const u = ['B','KiB','MiB','GiB','TiB']; let i = 0;
  while (b >= 1024 && i < u.length - 1) { b /= 1024; i++; }
  return b.toFixed(i ? 1 : 0) + ' ' + u[i];
};
const PILL_OK = /^[A-Z_]+$/;
const pill = (s) => PILL_OK.test(String(s)) ?
  `<span class="pill ${s}">${s}</span>` : esc(s);
// Cell renderers returning plain values are HTML-escaped; only the
// pill() helper (validated charset) and sparkline() (a TAGGED object
// wrapping numeric-only SVG built in-page — a string prefix check
// would let user-chosen names smuggle markup) emit raw HTML.
const cell = (v) => {
  if (v && typeof v === 'object' && v.__svg) return v.__svg;
  return (typeof v === 'string' && v.startsWith('<span class="pill '))
    ? v : esc(v ?? '');
};
// rows with onRow get a click handler (drill-down): rows are stashed in
// window._rows and referenced by index — no user data inside handlers.
const table = (cols, rows, onRow) => {
  window._rows = rows;
  const tr = (r, i) => onRow
    ? `<tr class="click" onclick="${onRow}(window._rows[${i}])">` : '<tr>';
  return `<table><tr>${cols.map(c => `<th>${esc(c[0])}</th>`).join('')}</tr>` +
    rows.map((r, i) => tr(r, i) + cols.map(c =>
      `<td>${cell(c[1](r))}</td>`).join('') + '</tr>').join('') +
    '</table>';
};
const detailPanel = (title, obj) => {
  if (!obj) return '';
  const rows = Object.entries(obj).map(([k, v]) =>
    `<tr><td>${esc(k)}</td><td>${esc(
      typeof v === 'object' && v !== null ? JSON.stringify(v) : v ?? ''
    )}</td></tr>`).join('');
  return `<div class="detail"><span class="close" ` +
    `onclick="detail=null;refresh()">✕ close</span>` +
    `<h3>${esc(title)}</h3><table>${rows}</table></div>`;
};
window.showDetail = (r) => { detail = r; refresh(); };
let forceRender = false;
window.showLog = (r) => { logFile = r.name; forceRender = true; refresh(); };
async function j(url) { const r = await fetch(url);
  if (!r.ok) throw new Error(url + ': ' + r.status); return r.json(); }

// --- timeline renderer: lanes per worker, bars per task span ---------
// Zoom with the mouse wheel (around the cursor), pan by dragging; view
// state persists across the 3s auto-refresh.
const laneColor = (name) => {
  let h = 0;
  for (const ch of String(name)) h = (h * 31 + ch.charCodeAt(0)) >>> 0;
  return `hsl(${h % 360} 60% 55%)`;
};
let tlWindow = 0;  // seconds of trailing window; 0 = everything
let tlV0 = 0, tlV1 = 1;  // zoom view as fractions of the full range
let metricSel = '';      // Metrics tab: currently-charted metric key
window.setTlWindow = (s) => { tlWindow = s; tlV0 = 0; tlV1 = 1; refresh(); };
window.tlReset = () => { tlV0 = 0; tlV1 = 1; refresh(); };
function renderTimeline(events) {
  let spans = events.filter(e => e.ph === 'X' && e.dur > 0);
  if (!spans.length) return '<p>No task events yet.</p>';
  if (tlWindow > 0) {
    let tmax = -Infinity;
    for (const e of spans) if (e.ts + e.dur > tmax) tmax = e.ts + e.dur;
    const cut = tmax - tlWindow * 1e6;
    spans = spans.filter(e => e.ts + e.dur >= cut);
    if (!spans.length) return '<p>No spans in this window.</p>';
  }
  // reduce, not spread: >~120k args would overflow the JS call stack
  let t0 = Infinity, t1 = -Infinity;
  for (const e of spans) {
    if (e.ts < t0) t0 = e.ts;
    if (e.ts + e.dur > t1) t1 = e.ts + e.dur;
  }
  const total = Math.max(t1 - t0, 1);
  // visible window in event time
  const vt0 = t0 + tlV0 * total, vt1 = t0 + tlV1 * total;
  const vtotal = Math.max(vt1 - vt0, 1);
  const lanes = new Map();
  let visible = 0;
  for (const e of spans) {
    if (e.ts + e.dur < vt0 || e.ts > vt1) continue;  // cull to view
    const key = e.pid || '?';
    if (!lanes.has(key)) lanes.set(key, []);
    lanes.get(key).push(e);
    visible++;
  }
  const winBtn = (s, label) =>
    `<button onclick="setTlWindow(${s})" style="margin-left:6px;` +
    `${tlWindow === s ? 'font-weight:700;' : ''}">${label}</button>`;
  const zoomed = tlV0 > 0 || tlV1 < 1;
  let html = `<div class="tl-axis">${(vtotal / 1e6).toFixed(3)}s shown` +
    (zoomed ? ` of ${(total / 1e6).toFixed(3)}s` : '') +
    ` &middot; ${visible} spans &middot; ${lanes.size} workers ` +
    `&middot; window:${winBtn(0, 'all')}${winBtn(60, '60s')}` +
    `${winBtn(10, '10s')}` +
    (zoomed ? ` <button onclick="tlReset()">reset zoom</button>` : '') +
    ` <span style="color:#9fb0c0">(wheel = zoom, drag = pan)</span>` +
    `</div><div class="tl-wrap" id="tlwrap"><div class="tl">`;
  for (const [key, evs] of lanes) {
    html += `<div class="tl-row"><div class="tl-lane-label">` +
      `${esc(key)}</div><div class="tl-track">`;
    // Cull-then-cap: zooming in reveals spans the cap hid at full view.
    for (const e of evs.slice(0, 2000)) {
      const left = ((e.ts - vt0) / vtotal * 100);
      const w = Math.max(e.dur / vtotal * 100, 0.05);
      // Clamp the left and RIGHT edges jointly: a span starting far
      // before the zoom window must keep its true right edge, not
      // stretch to left+110%.
      const l2 = Math.max(left, -5);
      const right = Math.min(left + w, 110);
      const w2 = Math.max(right - l2, 0.05);
      const failed = (e.args || {}).end_state === 'FAILED';
      const color = failed ? '#c0392b' : laneColor(e.name);
      const tip = `${e.name}  ${(e.dur / 1000).toFixed(2)}ms` +
        (failed ? '  FAILED' : '');
      html += `<div class="tl-bar" title="${esc(tip)}" style="left:` +
        `${l2.toFixed(3)}%;width:${w2.toFixed(3)}` +
        `%;background:${color}"></div>`;
    }
    html += '</div></div>';
  }
  return html + '</div></div>';
}
let tlDragging = false;  // pauses auto-refresh while panning
function wireTimeline() {
  const wrap = $('#tlwrap');
  if (!wrap) return;
  wrap.addEventListener('wheel', (e) => {
    e.preventDefault();
    const track = wrap.querySelector('.tl-track');
    if (!track) return;
    const r = track.getBoundingClientRect();
    const fx = Math.min(Math.max((e.clientX - r.left) / r.width, 0), 1);
    const span = tlV1 - tlV0;
    const factor = e.deltaY < 0 ? 0.8 : 1.25;
    const ns = Math.min(Math.max(span * factor, 1e-4), 1);
    const c = tlV0 + fx * span;
    tlV0 = Math.max(0, c - fx * ns);
    tlV1 = Math.min(1, tlV0 + ns);
    tlV0 = Math.max(0, tlV1 - ns);
    refresh();
  }, { passive: false });
  // Pan: live CSS shift during the drag (no re-render — that would
  // destroy these listeners), commit the new view on mouseup.
  let startX = null;
  wrap.addEventListener('mousedown', (e) => {
    startX = e.clientX; tlDragging = true; e.preventDefault();
  });
  wrap.addEventListener('mousemove', (e) => {
    if (startX === null) return;
    const dx = e.clientX - startX;
    wrap.querySelectorAll('.tl-track').forEach(t =>
      t.style.transform = `translateX(${dx}px)`);
  });
  const finish = (e) => {
    if (startX === null) return;
    const track = wrap.querySelector('.tl-track');
    const width = track ? track.getBoundingClientRect().width : 1;
    const frac = (e.clientX - startX) / width;
    startX = null; tlDragging = false;
    const span = tlV1 - tlV0;
    let v0 = tlV0 - frac * span;
    v0 = Math.min(Math.max(v0, 0), 1 - span);
    tlV0 = v0; tlV1 = v0 + span;
    refresh();
  };
  wrap.addEventListener('mouseup', finish);
  wrap.addEventListener('mouseleave', finish);
}

// --- metric history + sparklines (client-side time series; the
// reference embeds Grafana panels — here each refresh appends the
// node gauges to an in-page ring so trends render without a TSDB) ----
const METRIC_HISTORY = 120;  // samples (~6 min at the 3s refresh)
const metricHist = new Map();  // key -> [values]
function recordMetric(key, value) {
  if (typeof value !== 'number' || !isFinite(value)) return;
  let h = metricHist.get(key);
  if (!h) { h = []; metricHist.set(key, h); }
  h.push(value);
  if (h.length > METRIC_HISTORY) h.shift();
}
function sparkline(key, width = 120, height = 26) {
  const h = metricHist.get(key) || [];
  if (h.length < 2) return '';
  let min = Math.min(...h), max = Math.max(...h);
  if (max === min) { max += 1; }
  const pts = h.map((v, i) =>
    `${(i / (h.length - 1) * width).toFixed(1)},` +
    `${(height - 2 - (v - min) / (max - min) * (height - 4)).toFixed(1)}`
  ).join(' ');
  return { __svg: `<svg width="${width}" height="${height}" ` +
    `style="vertical-align:middle"><polyline points="${pts}" ` +
    `fill="none" stroke="#4a7dba" stroke-width="1.5"/></svg>` };
}

const views = {
  async overview() {
    const [cs, stats] = await Promise.all(
      [j('/api/cluster_status'), j('/api/node_stats')]);
    const res = cs.resources || {};
    for (const row of stats) {
      recordMetric(row.node_id + ':cpu', row['node.cpu_percent']);
      recordMetric(row.node_id + ':mem', row['node.mem_available_bytes']);
      recordMetric(row.node_id + ':store',
                   row['node.object_store_used_bytes']);
    }
    const cards = [
      ['nodes alive', `${cs.nodes_alive}/${cs.nodes_total}`],
      ['CPUs', `${(res.available||{}).CPU ?? '?'} / ${(res.total||{}).CPU ?? '?'}`],
      ['TPUs', `${(res.available||{}).TPU ?? 0} / ${(res.total||{}).TPU ?? 0}`],
    ];
    let html = '<div class="cards">' + cards.map(([k, v]) =>
      `<div class="card"><div class="v">${esc(v)}</div>` +
      `<div class="k">${esc(k)}</div></div>`).join('') + '</div>';
    html += '<h3>Per-node hardware</h3>' + table([
      ['node', r => (r.node_id || '').slice(0, 8)],
      ['host', r => r.hostname],
      ['cpu %', r => r['node.cpu_percent']?.toFixed(1)],
      ['cpu trend', r => sparkline(r.node_id + ':cpu')],
      ['mem avail', r => fmtBytes(r['node.mem_available_bytes'])],
      ['mem trend', r => sparkline(r.node_id + ':mem')],
      ['store used', r => fmtBytes(r['node.object_store_used_bytes'])],
      ['store trend', r => sparkline(r.node_id + ':store')],
      ['store cap', r => fmtBytes(r['node.object_store_capacity_bytes'])],
      ['tpu free/total', r => r['node.tpu_total'] ?
        `${r['node.tpu_available']}/${r['node.tpu_total']}` : '-'],
    ], stats);
    return html;
  },
  async nodes() {
    const nodes = await j('/api/nodes');
    return detailPanel('Node detail', detail) + table([
      ['node', r => (r.node_id || '').slice(0, 8)],
      ['state', r => pill(r.state)],
      ['address', r => r.address],
      ['slice', r => r.slice_id || '-'],
      ['cpu avail', r => (r.resources_available || {}).CPU],
      ['tpu avail', r => (r.resources_available || {}).TPU ?? '-'],
    ], nodes, 'showDetail');
  },
  async actors() {
    const actors = await j('/api/actors');
    return detailPanel('Actor detail', detail) + table([
      ['actor', r => (r.actor_id || '').slice(0, 8)],
      ['class', r => r.class_name],
      ['name', r => r.name || ''],
      ['state', r => pill(r.state)],
      ['restarts', r => r.num_restarts],
      ['node', r => (r.node_id || '').slice(0, 8)],
    ], actors, 'showDetail');
  },
  async pgs() {
    const pgs = await j('/api/placement_groups');
    return detailPanel('Placement group detail', detail) + table([
      ['pg', r => (r.pg_id || '').slice(0, 8)],
      ['name', r => r.name || ''],
      ['state', r => pill(r.state)],
      ['strategy', r => r.strategy],
      ['bundles', r => (r.bundles || []).length],
    ], pgs, 'showDetail');
  },
  async jobs() {
    const jobs = await j('/api/jobs');
    return detailPanel('Job detail', detail) + table([
      ['job', r => r.submission_id || r.job_id],
      ['status', r => pill(r.status || r.state)],
      ['entrypoint', r => r.entrypoint || ''],
    ], jobs, 'showDetail');
  },
  async tasks() {
    const [summary, rows] = await Promise.all(
      [j('/api/tasks/summary'), j('/api/tasks')]);
    let html = '<div class="cards">' +
      Object.entries(summary).map(([k, v]) =>
        `<div class="card"><div class="v">${esc(v)}</div>` +
        `<div class="k">${esc(k)}</div></div>`).join('') + '</div>';
    html += detailPanel('Task detail', detail) + table([
      ['task', r => (r.task_id || '').slice(0, 12)],
      ['name', r => r.name],
      ['state', r => pill(r.state)],
      ['actor', r => r.actor_id ? String(r.actor_id).slice(0, 8) : '-'],
      ['worker', r => (r.worker_id || '').slice(0, 8)],
    ], rows.slice(-500).reverse(), 'showDetail');
    return html;
  },
  async timeline() {
    const events = await j('/api/timeline');
    return renderTimeline(events);
  },
  async serve() {
    // Serve panel: app/deployment states + per-deployment request /
    // error / latency series from the metrics registry.
    const [st, samples] = await Promise.all(
      [j('/api/serve/applications'), j('/api/metrics_json')]);
    const byDep = {};
    for (const m of samples) {
      const dep = (m.tags || {}).deployment;
      if (!dep || !m.name.startsWith('serve_deployment_')) continue;
      const row = byDep[dep] = byDep[dep] ||
        {requests: 0, errors: 0, latency: null};
      if (m.name === 'serve_deployment_request_counter')
        row.requests += m.value;
      else if (m.name === 'serve_deployment_error_counter')
        row.errors += m.value;
      else if (m.name === 'serve_deployment_processing_latency_ms' &&
               m.count) row.latency = (m.sum / m.count);
    }
    const apps = st.applications || {};
    if (!Object.keys(apps).length)
      return '<p>no serve applications</p>';
    let html = '';
    for (const [app, info] of Object.entries(apps)) {
      html += `<h3>${esc(app)} ${pill(info.status)} ` +
        `<span style="font-weight:normal">${esc(info.route_prefix ?? '')}` +
        `</span></h3>`;
      const deps = Object.entries(info.deployments || {}).map(
        ([dn, di]) => ({name: dn, ...di, ...(byDep[dn] || {})}));
      html += table([
        ['deployment', r => r.name],
        ['status', r => pill(r.status)],
        ['replicas', r => Object.entries(r.replica_states || {})
          .map(([s, n]) => `${s}:${n}`).join(' ') || '-'],
        ['requests', r => r.requests ?? 0],
        ['errors', r => r.errors ?? 0],
        ['avg latency', r => r.latency != null ?
          r.latency.toFixed(1) + ' ms' : '-'],
        ['message', r => r.message || ''],
      ], deps);
    }
    return html;
  },
  async metrics() {
    // Metric explorer (reference: the Grafana panels in the dashboard
    // metrics module): every runtime/user metric accumulates history
    // client-side; pick one to chart it large.
    const samples = await j('/api/metrics_json');
    for (const m of samples) {
      const tags = Object.entries(m.tags || {}).sort()
        .map(([k, v]) => `${k}=${v}`).join(',');
      recordMetric('m:' + m.name + (tags ? `{${tags}}` : ''), m.value);
    }
    const keys = [...metricHist.keys()].filter(k => k.startsWith('m:'))
      .sort();
    if (!keys.length) return '<p>No metrics reported yet.</p>';
    if (!keys.includes(metricSel)) metricSel = keys[0];
    const opts = keys.map(k =>
      `<option value="${esc(k)}"${k === metricSel ? ' selected' : ''}>` +
      `${esc(k.slice(2))}</option>`).join('');
    const h = metricHist.get(metricSel) || [];
    const last = h.length ? h[h.length - 1] : NaN;
    const min = h.length ? Math.min(...h) : NaN;
    const max = h.length ? Math.max(...h) : NaN;
    const chart = sparkline(metricSel, 860, 180);
    return `<p><select id="metricsel" onchange="metricSel=this.value;` +
      `this.blur();forceRender=true;refresh()">${opts}</select>` +
      ` &nbsp; last=${esc(last)} ` +
      `min=${esc(min)} max=${esc(max)} (${h.length} samples)</p>` +
      `<div>${chart && chart.__svg ? chart.__svg :
             'collecting samples…'}</div>`;
  },
  async events() {
    const evs = await j('/api/events');
    return detailPanel('Event detail', detail) + table([
      ['time', r => new Date(r.timestamp * 1000).toLocaleTimeString()],
      ['severity', r => pill(r.severity)],
      ['source', r => r.source],
      ['type', r => r.event_type],
      ['message', r => r.message],
    ], evs.slice(-500).reverse(), 'showDetail');
  },
  async logs() {
    if (logFile) {
      return `<p><a href="#" onclick="logFile=null;logGen++;refresh();` +
        `return false">&larr; all logs</a> &nbsp; <b>${esc(logFile)}` +
        `</b> (live tail — long-poll push)</p>` +
        `<pre class="log" id="logpre">connecting…</pre>`;
    }
    const files = await j('/api/logs');
    return table([
      ['file', r => r.name],
      ['size', r => fmtBytes(r.size_bytes)],
    ], files, 'showLog');
  },
};

// --- push-style log following: long-poll /api/logs/stream ------------
let logGen = 0;  // bumped whenever the tailed file changes / tab leaves
async function followLog(file) {
  const gen = ++logGen;
  let offset = -1;
  while (gen === logGen && tab === 'logs' && logFile === file) {
    let res;
    try {
      const r = await fetch('/api/logs/stream?file=' +
        encodeURIComponent(file) + '&offset=' + offset + '&wait_s=20');
      if (!r.ok) throw new Error('stream: ' + r.status);
      res = await r.json();
    } catch (e) {
      const pre = $('#logpre');
      if (pre && gen === logGen) pre.textContent += '\\n[stream error: '
        + e + ']';
      await new Promise(ok => setTimeout(ok, 2000));
      continue;
    }
    if (gen !== logGen) return;
    const pre = $('#logpre');
    if (!pre) return;
    if (offset === -1) pre.textContent = '';
    offset = res.offset;
    if (res.data) {
      const stick = pre.scrollTop + pre.clientHeight >=
        pre.scrollHeight - 8;
      pre.textContent = (pre.textContent + res.data).slice(-400000);
      if (stick) pre.scrollTop = pre.scrollHeight;
    }
  }
}

async function refresh() {
  // Never clobber an interactive view: mid-pan timeline or a streaming
  // log tail (the long-poll loop updates the <pre> in place).
  if (tlDragging) return;
  if (!forceRender && tab === 'logs' && logFile && $('#logpre')) return;
  // Don't rebuild the Metrics tab while its dropdown is open.
  if (document.activeElement && document.activeElement.id === 'metricsel'
      && !forceRender) return;
  forceRender = false;
  try {
    $('#content').innerHTML = await views[tab]();
    $('#ts').textContent = new Date().toLocaleTimeString();
    $('#err').textContent = '';
    if (tab === 'timeline') wireTimeline();
    if (tab === 'logs' && logFile) followLog(logFile);
  } catch (e) { $('#err').textContent = String(e); }
}
document.querySelectorAll('nav button').forEach(b =>
  b.addEventListener('click', () => {
    document.querySelectorAll('nav button').forEach(x =>
      x.classList.remove('active'));
    b.classList.add('active');
    tab = b.dataset.tab;
    detail = null;
    refresh();
  }));
refresh();
setInterval(refresh, 3000);
</script>
</body>
</html>
"""
