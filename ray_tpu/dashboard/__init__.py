"""ray_tpu.dashboard — HTTP observability head.

Parity target: python/ray/dashboard/ (head + state aggregation +
Prometheus metrics export). JSON state endpoints + /metrics text; the
reference's React frontend is out of scope — the endpoints carry the
same data the state CLI/SDK uses.
"""

from ray_tpu.dashboard.head import DashboardHead, start_dashboard

__all__ = ["DashboardHead", "start_dashboard"]
