"""Dashboard head: aiohttp server over GCS state.

Reference: python/ray/dashboard/head.py + http_server_head.py (state
endpoints, /metrics Prometheus via metrics_agent.py:244). Runs in a
thread beside the driver or the CLI head process.

Endpoints:
  GET /                     -> single-page UI (dashboard/ui.py)
  GET /healthz              -> "success"
  GET /metrics              -> Prometheus text (user + runtime metrics)
  GET /api/cluster_status   -> nodes + resources
  GET /api/nodes            -> node table
  GET /api/actors           -> actor table
  GET /api/jobs             -> submitted jobs
  GET /api/tasks/summary    -> task state counts
  GET /api/node_stats       -> per-node hardware gauges (reporter loop)
  GET /api/timeline         -> chrome trace JSON
  GET /api/tasks            -> per-task latest-state rows
  GET /api/placement_groups -> placement group table
  GET /api/objects          -> object location table
  GET/PUT/DELETE /api/serve/applications -> Serve REST API (status /
      declarative deploy of a ServeDeploySchema dict / teardown)
  GET /api/logs             -> session log file listing
  GET /api/logs/tail?file=X&lines=N -> tail one log file
  GET /api/logs/stream?file=X&offset=N&wait_s=S -> long-poll incremental
      tail: returns {offset, data} as soon as the file grows past
      `offset` (or after wait_s with empty data) — push-style tailing
      without websockets
  GET /api/grafana_dashboard -> Grafana dashboard JSON generated from
      the live metric registry (dashboard/metrics_module.py)
  GET /api/prometheus_scrape_config -> prometheus.yml text targeting
      this head's /metrics
  GET /api/v0/state/engines  -> serving state API: live engine rows
  GET /api/v0/state/requests?status=X&engine_id=Y -> in-flight request
      rows (status: queued|prefilling|decoding|swapped|draining)
  GET /api/v0/state/kv_pools -> KV block-pool / prefix-pool occupancy
  GET /api/v0/state/summary  -> `ray status`-shaped fleet rollup
  GET /api/v0/metrics_history -> bounded time-series ring of serving
      gauges (each hit also records one sample, cadence-guarded)
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


def _prometheus_text(metrics: List[Dict[str, Any]]) -> str:
    # Canonical renderer lives beside the registry so local
    # (util.metrics.prometheus_text) and cluster-wide (this route)
    # exposition can never drift; it also groups each metric's series
    # contiguously, which the exposition format requires and the old
    # in-place renderer got wrong for interleaved GCS rows.
    from ray_tpu._private.metrics import prometheus_text

    return prometheus_text(metrics)


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._thread: Optional[threading.Thread] = None
        self._loop = None
        self._started = threading.Event()
        self._stop_evt: Optional[asyncio.Event] = None

    # ---- data helpers (worker-thread safe: gcs_call is sync) ----

    def _gcs(self, method: str, data: Optional[dict] = None):
        from ray_tpu._private.worker import global_worker

        return global_worker().gcs_call(method, data or {})

    # ---- aiohttp app ----

    async def _serve(self) -> None:
        from aiohttp import web

        routes = web.RouteTableDef()

        def offload(fn, *args):
            return asyncio.get_running_loop().run_in_executor(
                None, fn, *args)

        @routes.get("/")
        async def index(request):
            from ray_tpu.dashboard.ui import INDEX_HTML

            return web.Response(text=INDEX_HTML,
                                content_type="text/html")

        @routes.get("/healthz")
        async def healthz(request):
            return web.Response(text="success")

        @routes.get("/api/node_stats")
        async def node_stats(request):
            """Per-node hardware gauges from the raylet reporters
            (reference: dashboard/modules/reporter/)."""
            data = await offload(self._gcs, "get_metrics")
            per_node: Dict[str, Dict[str, Any]] = {}
            for m in data or []:
                if not m["name"].startswith("node."):
                    continue
                node = m.get("tags", {}).get("node_id", "?")
                row = per_node.setdefault(node, {
                    "node_id": node,
                    "hostname": m.get("tags", {}).get("hostname", "")})
                row[m["name"]] = m["value"]
            return web.json_response(list(per_node.values()),
                                     dumps=_dumps)

        @routes.get("/metrics")
        async def metrics(request):
            data = await offload(self._gcs, "get_metrics")
            return web.Response(text=_prometheus_text(data or []),
                                content_type="text/plain")

        @routes.get("/api/grafana_dashboard")
        async def grafana_dashboard_route(request):
            """Grafana dashboard JSON generated from the LIVE registry
            (reference: dashboard/modules/metrics/metrics_head.py:68) —
            panels can only reference series /metrics actually exports."""
            from ray_tpu.dashboard.metrics_module import grafana_dashboard

            data = await offload(self._gcs, "get_metrics")
            return web.json_response(grafana_dashboard(data or []),
                                     dumps=_dumps)

        @routes.get("/api/prometheus_scrape_config")
        async def prometheus_scrape_route(request):
            from ray_tpu.dashboard.metrics_module import \
                prometheus_scrape_config

            return web.Response(
                text=prometheus_scrape_config(
                    f"{self.host}:{self.port}"),
                content_type="text/plain")

        @routes.get("/api/metrics_json")
        async def metrics_json(request):
            """Raw metric samples for the UI's Metrics tab (reference:
            the Grafana panels in dashboard/modules/metrics — here the
            page itself keeps the history ring)."""
            return web.json_response(
                await offload(self._gcs, "get_metrics") or [],
                dumps=_dumps)

        # ---- serving state API (ray_tpu.util.state.serving) ----
        # These read the HEAD PROCESS's registrations: engines/fleets
        # constructed in this process (driver-embedded dashboard, the
        # CPU dry-run topology, tests). Pure host snapshots, offloaded
        # off the event loop like every other route.

        @routes.get("/api/v0/state/engines")
        async def state_engines(request):
            from ray_tpu.util.state import serving

            return web.json_response(
                await offload(serving.list_engines), dumps=_dumps)

        @routes.get("/api/v0/state/requests")
        async def state_requests(request):
            from ray_tpu.util.state import serving

            status = request.query.get("status") or None
            engine_id = request.query.get("engine_id") or None
            try:
                rows = await offload(
                    lambda: serving.list_requests(
                        status=status, engine_id=engine_id))
            except ValueError as e:
                return web.Response(status=400, text=str(e))
            return web.json_response(rows, dumps=_dumps)

        @routes.get("/api/v0/state/kv_pools")
        async def state_kv_pools(request):
            from ray_tpu.util.state import serving

            return web.json_response(
                await offload(serving.list_kv_pools), dumps=_dumps)

        @routes.get("/api/v0/state/summary")
        async def state_summary(request):
            from ray_tpu.util.state import serving

            return web.json_response(
                await offload(serving.summarize_fleet), dumps=_dumps)

        @routes.get("/api/v0/metrics_history")
        async def metrics_history_route(request):
            """Pull-driven history: every hit records one sample into
            the global ring (the cadence guard makes aggressive polling
            harmless) and returns the retained window."""
            from ray_tpu.util import metrics_history as mh

            def sample_and_dump():
                mh.sample_now()
                return mh.global_history().snapshot()

            return web.json_response(await offload(sample_and_dump),
                                     dumps=_dumps)

        @routes.get("/api/cluster_status")
        async def cluster_status(request):
            res = await offload(self._gcs, "cluster_resources")
            nodes = await offload(self._gcs, "get_nodes")
            alive = sum(1 for n in nodes if n.get("state") == "ALIVE")
            return web.json_response({
                "nodes_alive": alive, "nodes_total": len(nodes),
                "resources": res}, dumps=_dumps)

        @routes.get("/api/nodes")
        async def nodes(request):
            from ray_tpu.util import state

            return web.json_response(
                await offload(state.list_nodes), dumps=_dumps)

        @routes.get("/api/actors")
        async def actors(request):
            from ray_tpu.util import state

            return web.json_response(
                await offload(state.list_actors), dumps=_dumps)

        @routes.get("/api/jobs")
        async def jobs(request):
            from ray_tpu.job_submission import JobSubmissionClient

            client = JobSubmissionClient()
            infos = await offload(client.list_jobs)
            return web.json_response([i.__dict__ for i in infos],
                                     dumps=_dumps)

        @routes.get("/api/tasks/summary")
        async def tasks_summary(request):
            from ray_tpu.util import state

            return web.json_response(
                await offload(state.summarize_tasks), dumps=_dumps)

        @routes.get("/api/timeline")
        async def timeline_route(request):
            from ray_tpu.util.timeline import timeline

            return web.json_response(await offload(timeline),
                                     dumps=_dumps)

        @routes.get("/api/tasks")
        async def tasks_route(request):
            from ray_tpu.util import state

            return web.json_response(
                await offload(state.list_tasks), dumps=_dumps)

        @routes.get("/api/placement_groups")
        async def pgs_route(request):
            from ray_tpu.util import state

            return web.json_response(
                await offload(state.list_placement_groups), dumps=_dumps)

        @routes.get("/api/events")
        async def events_route(request):
            """Structured cluster events (reference: dashboard event
            module over event.proto exports)."""
            return web.json_response(
                await offload(self._gcs, "list_events", {"limit": 1000}),
                dumps=_dumps)

        @routes.get("/api/objects")
        async def objects_route(request):
            from ray_tpu.util import state

            return web.json_response(
                await offload(state.list_objects), dumps=_dumps)

        def _log_dir() -> str:
            from ray_tpu._private.worker import global_worker

            return os.path.join(global_worker().core.session_dir, "logs")

        @routes.get("/api/logs")
        async def logs_route(request):
            """Session log files (reference: dashboard log module /
            `ray logs`)."""
            def ls():
                d = _log_dir()
                out = []
                for name in sorted(os.listdir(d)) if os.path.isdir(d) \
                        else []:
                    try:
                        out.append({"name": name, "size_bytes":
                                    os.path.getsize(os.path.join(d, name))})
                    except OSError:
                        pass
                return out

            return web.json_response(await offload(ls), dumps=_dumps)

        @routes.get("/api/logs/tail")
        async def logs_tail(request):
            name = os.path.basename(request.query.get("file", ""))
            try:
                n = int(request.query.get("lines", "200"))
            except ValueError:
                return web.Response(status=400, text="bad lines param")
            n = max(1, min(n, 5000))

            def tail():
                path = os.path.join(_log_dir(), name)
                if not os.path.isfile(path):
                    return None
                with open(path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - 512 * 1024))
                    data = f.read().decode("utf-8", "replace")
                return "\n".join(data.splitlines()[-n:])

            text = await offload(tail)
            if text is None:
                return web.Response(status=404, text="no such log file")
            return web.Response(text=text, content_type="text/plain")

        @routes.get("/api/serve/applications")
        async def serve_apps(request):
            """Serve REST API (reference: dashboard serve module /
            `serve status`): live application/deployment states."""
            def get_status():
                from ray_tpu import serve

                return serve.status()

            return web.json_response(await offload(get_status),
                                     dumps=_dumps)

        @routes.put("/api/serve/applications")
        async def serve_deploy(request):
            """Declarative deploy (reference: PUT /api/serve/applications
            — `serve deploy` over REST): body is a ServeDeploySchema
            dict; apps are (re)deployed to match it."""
            try:
                body = await request.json()
            except Exception:
                return web.Response(status=400, text="invalid JSON body")

            def deploy():
                from ray_tpu.serve.schema import (ServeDeploySchema,
                                                  deploy_from_schema)

                schema = ServeDeploySchema.from_dict(body)
                deploy_from_schema(schema)
                return {"deployed": [a.name for a in schema.applications]}

            try:
                return web.json_response(await offload(deploy),
                                         dumps=_dumps)
            except Exception as e:
                return web.Response(status=400,
                                    text=f"{type(e).__name__}: {e}")

        @routes.delete("/api/serve/applications")
        async def serve_teardown(request):
            """Tear down one app (?name=X) or every app."""
            name = request.query.get("name", "")

            def teardown():
                from ray_tpu import serve

                if name:
                    serve.delete(name)
                else:
                    serve.shutdown()
                return {"deleted": name or "all"}

            try:
                return web.json_response(await offload(teardown),
                                         dumps=_dumps)
            except Exception as e:
                return web.Response(status=400,
                                    text=f"{type(e).__name__}: {e}")

        @routes.get("/api/logs/stream")
        async def logs_stream(request):
            """Long-poll incremental tail (push-style log following —
            reference: dashboard log module's streaming reads). The
            client passes the offset it has consumed to; the reply
            carries bytes from there and the new offset. offset=-1
            means "start near the tail"."""
            name = os.path.basename(request.query.get("file", ""))
            path = os.path.join(_log_dir(), name)
            try:
                offset = int(request.query.get("offset", "-1"))
                wait_s = min(float(request.query.get("wait_s", "25")), 55.0)
            except ValueError:
                return web.Response(status=400, text="bad params")
            if not os.path.isfile(path):
                return web.Response(status=404, text="no such log file")

            def read_from(pos: int):
                size = os.path.getsize(path)
                if pos < 0 or size < pos:
                    # First call — or the file was truncated/rotated
                    # under us (size shrank past our offset): resume
                    # near the new tail instead of stalling forever.
                    pos = max(0, size - 64 * 1024)
                if size <= pos:
                    return pos, ""
                with open(path, "rb") as f:
                    f.seek(pos)
                    data = f.read(512 * 1024)
                return pos + len(data), data.decode("utf-8", "replace")

            deadline = asyncio.get_running_loop().time() + wait_s
            new_off, data = await offload(read_from, offset)
            while not data and offset >= 0 and \
                    asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.3)
                new_off, data = await offload(read_from, offset)
            return web.json_response({"offset": new_off, "data": data})

        app = web.Application()
        app.add_routes(routes)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self._stop_evt = asyncio.Event()
        self._started.set()
        logger.info("dashboard listening on %s:%d", self.host, self.port)
        await self._stop_evt.wait()
        await runner.cleanup()

    def start(self) -> "DashboardHead":
        self._error: Optional[BaseException] = None

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            try:
                self._loop.run_until_complete(self._serve())
            except BaseException as e:
                self._error = e
                self._started.set()  # unblock the waiter with the error
            finally:
                self._loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="dashboard-head")
        self._thread.start()
        if not self._started.wait(10.0):
            raise RuntimeError("dashboard failed to start (timeout)")
        if self._error is not None:
            raise RuntimeError(
                f"dashboard failed to start on {self.host}:{self.port}: "
                f"{type(self._error).__name__}: {self._error}"
            ) from self._error
        return self

    def stop(self) -> None:
        if self._loop and self._stop_evt:
            self._loop.call_soon_threadsafe(self._stop_evt.set)
        if self._thread:
            self._thread.join(timeout=5.0)


def _dumps(obj) -> str:
    return json.dumps(obj, default=lambda o: o.hex()
                      if isinstance(o, bytes) else str(o))


def start_dashboard(host: str = "127.0.0.1",
                    port: int = 8265) -> DashboardHead:
    return DashboardHead(host, port).start()
