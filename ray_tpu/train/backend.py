"""Backend plugin interface.

Reference: python/ray/train/backend.py:32 (`Backend` with
on_start/on_training_start/on_shutdown hooks run by the BackendExecutor).
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ray_tpu.train._internal.worker_group import WorkerGroup


@dataclasses.dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Framework-setup hooks around a WorkerGroup's lifetime."""

    def on_start(self, worker_group: "WorkerGroup",
                 backend_config: BackendConfig) -> None:
        pass

    def on_training_start(self, worker_group: "WorkerGroup",
                          backend_config: BackendConfig) -> None:
        pass

    def on_shutdown(self, worker_group: "WorkerGroup",
                    backend_config: BackendConfig) -> None:
        pass
