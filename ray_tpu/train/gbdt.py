"""XGBoostTrainer / LightGBMTrainer — gated GBDT trainers.

Reference: python/ray/train/xgboost/xgboost_trainer.py and
python/ray/train/lightgbm/lightgbm_trainer.py (GBDTTrainer base in
python/ray/train/gbdt_trainer.py). The reference delegates distributed
boosting to the external xgboost_ray / lightgbm_ray packages; neither
xgboost nor lightgbm ships in this image, so these trainers are
import-gated: constructing one without the library raises an informative
ImportError. When the library IS present, the fit runs the estimator's
sklearn-compatible API inside one train worker on the same
session/report/checkpoint infra as SklearnTrainer.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ray_tpu.air import Result, RunConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.sklearn_trainer import SklearnTrainer

__all__ = ["XGBoostTrainer", "LightGBMTrainer"]


class _GBDTTrainer:
    _module: str = ""
    _estimator_attr: str = ""
    _classifier_attr: str = ""

    def __init__(self, *,
                 datasets: Dict[str, Any],
                 label_column: Optional[str] = None,
                 params: Optional[Dict[str, Any]] = None,
                 num_boost_round: int = 10,
                 run_config: Optional[RunConfig] = None):
        import importlib

        try:
            mod = importlib.import_module(self._module)
        except ImportError as e:
            raise ImportError(
                f"{type(self).__name__} requires the '{self._module}' "
                f"package, which is not installed in this environment. "
                f"Install it (e.g. `pip install {self._module}`) to use "
                f"this trainer; SklearnTrainer and JaxTrainer are "
                f"available without it.") from e

        params = dict(params or {})
        params.setdefault("n_estimators", num_boost_round)
        # objective picks the estimator flavor (reference passes the
        # objective straight to the native train() API).
        objective = str(params.get("objective", ""))
        attr = self._classifier_attr if objective.startswith(
            ("binary", "multi")) else self._estimator_attr
        estimator = getattr(mod, attr)(**params)
        self._inner = SklearnTrainer(
            estimator=estimator, datasets=datasets,
            label_column=label_column, run_config=run_config)

    def fit(self) -> Result:
        return self._inner.fit()

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        return SklearnTrainer.get_model(checkpoint)


class XGBoostTrainer(_GBDTTrainer):
    _module = "xgboost"
    _estimator_attr = "XGBRegressor"
    _classifier_attr = "XGBClassifier"


class LightGBMTrainer(_GBDTTrainer):
    _module = "lightgbm"
    _estimator_attr = "LGBMRegressor"
    _classifier_attr = "LGBMClassifier"
