"""Checkpoint -> batch inference seam.

Reference: python/ray/train/predictor.py:40 (``Predictor`` —
``from_checkpoint`` + ``predict`` over a batch) and
batch_predictor.py (checkpoint fanned over ``Dataset.map_batches`` with
an actor pool that loads the model ONCE per actor).

TPU-first divergence: ``JaxPredictor`` jits the apply function and can
device_put params onto a ``jax.sharding`` so per-batch inference rides
the mesh; the actor-pool fan-out is the host-level axis, GSPMD the
chip-level one.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ray_tpu.train.checkpoint import Checkpoint


class Predictor:
    """Load-once / predict-many (reference: train/predictor.py:40)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch: Dict[str, np.ndarray]
                ) -> Dict[str, np.ndarray]:
        """One numpy batch in, one numpy batch out."""
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Params + a jitted apply function.

    ``from_checkpoint`` accepts either a dict checkpoint holding
    ``{"params": pytree}`` (the JaxTrainer report path) or a sharded
    array checkpoint directory (array_checkpoint.save_pytree) when a
    ``template`` pytree is given.
    """

    def __init__(self, apply_fn: Callable, params: Any,
                 *, jit: bool = True, sharding=None):
        import jax

        if sharding is not None:
            params = jax.device_put(params, sharding)
        self._params = params
        self._apply = jax.jit(apply_fn) if jit else apply_fn

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable,
                        template: Any = None,
                        jit: bool = True,
                        sharding=None) -> "JaxPredictor":
        import os

        d = checkpoint.to_directory()
        if template is not None:
            from ray_tpu.train.array_checkpoint import restore_pytree

            params = restore_pytree(template, d)
        elif os.path.exists(os.path.join(d, Checkpoint._DICT_FILE)):
            state = checkpoint.to_dict()
            params = state.get("params", state)
        else:
            raise ValueError(
                f"checkpoint at {d} is neither a dict checkpoint nor "
                "was a `template` given for a sharded array checkpoint")
        return cls(apply_fn, params, jit=jit, sharding=sharding)

    def predict(self, batch: Dict[str, np.ndarray]
                ) -> Dict[str, np.ndarray]:
        out = self._apply(self._params, batch)
        if isinstance(out, dict):
            return {k: np.asarray(v) for k, v in out.items()}
        return {"predictions": np.asarray(out)}


class SklearnPredictor(Predictor):
    """Pickled-estimator checkpoints (SklearnTrainer.get_model)."""

    def __init__(self, estimator):
        self._estimator = estimator

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **_kw) -> "SklearnPredictor":
        from ray_tpu.train.sklearn_trainer import SklearnTrainer

        return cls(SklearnTrainer.get_model(checkpoint))

    def predict(self, batch: Dict[str, np.ndarray]
                ) -> Dict[str, np.ndarray]:
        X = np.column_stack([np.asarray(v) for v in batch.values()])
        return {"predictions": np.asarray(self._estimator.predict(X))}


class _ScoringActor:
    """map_batches class-UDF: constructs the predictor ONCE per pool
    actor (the reference's one-model-per-actor guarantee), then scores
    every batch routed to it."""

    def __init__(self, checkpoint_path: str, predictor_cls,
                 predictor_kwargs: dict, feature_columns,
                 keep_columns):
        self._predictor = predictor_cls.from_checkpoint(
            Checkpoint.from_directory(checkpoint_path),
            **predictor_kwargs)
        self._features = feature_columns
        self._keep = keep_columns or []

    def __call__(self, batch: Dict[str, np.ndarray]
                 ) -> Dict[str, np.ndarray]:
        feats = ({k: batch[k] for k in self._features}
                 if self._features else batch)
        out = self._predictor.predict(feats)
        for k in self._keep:
            out[k] = batch[k]
        return out


class BatchPredictor:
    """Checkpoint + Predictor class -> distributed inference over a
    Dataset (reference: train/batch_predictor.py). Each pool actor
    loads the checkpoint once; batches stream through the Data
    executor with its usual backpressure."""

    def __init__(self, checkpoint: Checkpoint, predictor_cls,
                 **predictor_kwargs):
        self._checkpoint = checkpoint
        self._predictor_cls = predictor_cls
        self._predictor_kwargs = predictor_kwargs

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, predictor_cls,
                        **predictor_kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **predictor_kwargs)

    def predict(self, dataset, *,
                batch_size: Optional[int] = 256,
                concurrency: int = 2,
                feature_columns: Optional[List[str]] = None,
                keep_columns: Optional[List[str]] = None):
        return dataset.map_batches(
            _ScoringActor,
            batch_size=batch_size,
            batch_format="numpy",
            concurrency=concurrency,
            fn_constructor_args=(
                self._checkpoint.to_directory(),
                self._predictor_cls,
                self._predictor_kwargs,
                feature_columns,
                keep_columns,
            ))
