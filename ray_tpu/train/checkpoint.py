"""Checkpoint — a directory of files, referenced by path.

Reference: python/ray/train/_checkpoint.py:56 (`Checkpoint` = directory +
pyarrow filesystem). Local/NFS/GCS-fuse paths are plain directories here;
sharded jax.Array checkpointing (per-host writes, orbax-style) is layered
on top in ray_tpu.train.jax.checkpointing.
"""

from __future__ import annotations

import contextlib
import json
import os
import pickle
import shutil
import tempfile
import uuid
from typing import Any, Dict, Iterator, Optional


class Checkpoint:
    """A directory snapshot. Create with `from_directory` (takes ownership
    of the path) or `from_dict` (writes a pickle into a temp dir)."""

    _DICT_FILE = "_dict_checkpoint.pkl"

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # ---- constructors ----
    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        d = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        with open(os.path.join(d, cls._DICT_FILE), "wb") as f:
            pickle.dump(data, f)
        return cls(d)

    # ---- accessors ----
    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None or os.path.abspath(path) == self.path:
            return self.path
        os.makedirs(path, exist_ok=True)
        shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self) -> Iterator[str]:
        yield self.path

    def to_dict(self) -> Dict[str, Any]:
        with open(os.path.join(self.path, self._DICT_FILE), "rb") as f:
            return pickle.load(f)

    def update_metadata(self, metadata: Dict[str, Any]) -> None:
        meta = self.get_metadata()
        meta.update(metadata)
        os.makedirs(self.path, exist_ok=True)
        with open(os.path.join(self.path, ".metadata.json"), "w") as f:
            json.dump(meta, f, default=str)

    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, ".metadata.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def __repr__(self) -> str:
        return f"Checkpoint(path={self.path})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))
