"""JaxConfig / _JaxBackend — the TPU analog of _TorchBackend.

Reference: python/ray/train/torch/config.py:150 (`_TorchBackend.on_start`
→ `_setup_torch_process_group` :65 with a rank-0 TCP store). Here the
rendezvous is `jax.distributed.initialize`: rank 0's address becomes the
coordinator; every worker gets (coordinator, num_processes, process_id)
and its JAX runtime joins one global device world over ICI/DCN. The
precedent in the reference for an XLA backend is
python/ray/train/torch/xla/config.py:120 (`_TorchAwsNeuronXLABackend`).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """jax_distributed: bootstrap a multi-process JAX world (one process
    per worker/host). Off for single-process or CPU-test worlds."""

    jax_distributed: bool = True
    coordinator_port: Optional[int] = None

    @property
    def backend_cls(self):
        return _JaxBackend


def _init_jax_distributed(coordinator_address: str, num_processes: int,
                          process_id: int) -> dict:
    from ray_tpu.parallel.bootstrap import initialize_distributed

    info = initialize_distributed(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    return dataclasses.asdict(info)


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig) -> None:
        if not backend_config.jax_distributed or len(worker_group) <= 1:
            return
        infos = worker_group.execute("get_node_info")
        port = backend_config.coordinator_port or infos[0]["free_port"]
        coordinator = f"{infos[0]['ip']}:{port}"
        import ray_tpu

        refs = [
            w.run_fn.remote(_init_jax_distributed, coordinator,
                            len(worker_group), rank)
            for rank, w in enumerate(worker_group.workers)
        ]
        ray_tpu.get(refs)
