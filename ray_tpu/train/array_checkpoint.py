"""Sharded jax.Array checkpointing — orbax/tensorstore-style layout.

Reference role: python/ray/train checkpoints hold torch state dicts; the
TPU-native equivalent must persist GSPMD-sharded arrays. Design:

- save: every host writes only its OWN addressable shards (no gather —
  checkpoint bandwidth scales with hosts), deduplicated by shard index
  (replicated leaves are written once per unique region, not once per
  device). Each process atomically publishes its own partial index
  (`array_index.p<k>.json`) after its data is on disk.
- restore: indexes from ALL processes are merged; a coverage mask
  guarantees every element of a requested region is backed by a shard
  file (a torn/partial checkpoint fails loudly, never returns
  uninitialized memory). `jax.make_array_from_callback` pulls exactly
  the slices each device needs, so a checkpoint saved under one
  mesh/sharding restores under a different one.

Durability note: a checkpoint is complete once every participating
process has published its partial index. Callers that need an explicit
commit point should barrier after save_pytree (e.g.
ray_tpu.collective.barrier) and then write their own marker.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

Pytree = Any

_INDEX_GLOB = "array_index.p*.json"


def _dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 / fp8 etc. live in ml_dtypes, not base numpy.
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _storable(a: np.ndarray) -> np.ndarray:
    """npy round-trips base dtypes only: exotic dtypes (bfloat16, fp8)
    are stored bit-cast to a same-width uint; the index's logical dtype
    restores the view on load."""
    try:
        np.dtype(str(a.dtype))
        return a
    except TypeError:
        return a.view(np.dtype(f"uint{a.dtype.itemsize * 8}"))


def _leaf_paths(tree: Pytree) -> List[Tuple[str, Any]]:
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for keypath, leaf in flat:
        name = "/".join(_key_str(k) for k in keypath)
        out.append((name, leaf))
    return out


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def _slices_to_json(index: Tuple[slice, ...], shape) -> List[List[int]]:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_pytree(tree: Pytree, path: str,
                process_index: Optional[int] = None) -> None:
    """Write this process's addressable shards of every leaf.

    Multi-host: every process calls this with the same (shared) path;
    shard files are keyed by (leaf ordinal, device id) so writers never
    collide, and each process publishes its own partial index."""
    import jax

    process_index = jax.process_index() if process_index is None \
        else process_index
    data_dir = os.path.join(path, "data")
    os.makedirs(data_dir, exist_ok=True)
    index: Dict[str, Any] = {"leaves": []}
    for ordinal, (name, leaf) in enumerate(_leaf_paths(tree)):
        arr = leaf
        dtype = getattr(arr, "dtype", None) or np.asarray(arr).dtype
        entry = {"name": name, "shape": list(np.shape(arr)),
                 "dtype": str(dtype), "shards": []}
        # File names use the leaf ordinal (collision-proof: user keys may
        # contain '/', '.', anything).
        prefix = f"leaf{ordinal:05d}"
        if hasattr(arr, "addressable_shards"):
            written = set()
            for shard in arr.addressable_shards:
                # Cross-host dedup of replicated regions: only the
                # replica_id==0 holder writes (orbax convention) — else
                # every host writes its own copy of fully-replicated
                # leaves and checkpoint bytes scale with host count.
                if getattr(shard, "replica_id", 0) != 0:
                    continue
                region = tuple(
                    tuple(b) for b in _slices_to_json(shard.index,
                                                      arr.shape))
                if region in written:
                    continue  # replicated copy — one write per region
                written.add(region)
                fname = f"{prefix}.d{shard.device.id}.npy"
                np.save(os.path.join(data_dir, fname),
                        _storable(np.asarray(shard.data)))
                entry["shards"].append({
                    "file": fname,
                    "index": [list(b) for b in region],
                })
        else:
            fname = f"{prefix}.p{process_index}.npy"
            np.save(os.path.join(data_dir, fname),
                    _storable(np.asarray(arr)))
            entry["shards"].append({
                "file": fname,
                "index": _slices_to_json(
                    tuple(slice(0, d) for d in np.shape(arr)),
                    np.shape(arr)),
            })
        index["leaves"].append(entry)
    # Publish this process's partial index atomically AFTER its data.
    final = os.path.join(path, f"array_index.p{process_index}.json")
    tmp = final + ".tmp"
    with open(tmp, "w") as f:
        json.dump(index, f)
    os.replace(tmp, final)


def _merged_index(path: str) -> Dict[str, dict]:
    """name -> entry with shards merged across every process's index."""
    files = sorted(glob.glob(os.path.join(path, _INDEX_GLOB)))
    if not files:
        raise FileNotFoundError(
            f"no {_INDEX_GLOB} under {path!r} — not a checkpoint")
    merged: Dict[str, dict] = {}
    for fname in files:
        with open(fname) as f:
            index = json.load(f)
        for entry in index["leaves"]:
            cur = merged.get(entry["name"])
            if cur is None:
                merged[entry["name"]] = {
                    **entry, "shards": list(entry["shards"])}
            else:
                seen = {json.dumps(s["index"]) for s in cur["shards"]}
                for s in entry["shards"]:
                    if json.dumps(s["index"]) not in seen:
                        cur["shards"].append(s)
    return merged


def _read_region(data_dir: str, entry: dict,
                 want: Tuple[slice, ...]) -> np.ndarray:
    """Assemble the requested region from overlapping shard files; every
    element must be covered (torn checkpoints fail, never return
    uninitialized memory)."""
    shape = entry["shape"]
    want_bounds = []
    for sl, dim in zip(want, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        want_bounds.append((int(start), int(stop)))
    out_shape = [b - a for a, b in want_bounds]
    out = np.empty(out_shape, dtype=_dtype(entry["dtype"]))
    covered = np.zeros(out_shape, dtype=bool)
    for shard in entry["shards"]:
        bounds = shard["index"]
        inter = []
        ok = True
        for (wa, wb), (sa, sb) in zip(want_bounds, bounds):
            a, b = max(wa, sa), min(wb, sb)
            if a >= b:
                ok = False
                break
            inter.append((a, b, sa, wa))
        if not ok:
            continue
        try:
            data = np.load(os.path.join(data_dir, shard["file"]))
        except OSError:
            continue  # missing/torn file -> coverage check reports it
        if data.dtype != out.dtype:
            data = data.view(out.dtype)  # exotic dtype stored bit-cast
        src = tuple(slice(a - sa, b - sa) for a, b, sa, _ in inter)
        dst = tuple(slice(a - wa, b - wa) for a, b, _, wa in inter)
        out[dst] = data[src]
        covered[dst] = True
    if not covered.all():
        raise ValueError(
            f"checkpoint region {want_bounds} of {entry['name']} is "
            "incomplete (missing shard files — all hosts' shards and "
            "indexes must be visible at restore)")
    return out


def restore_pytree(template: Pytree, path: str,
                   shardings: Optional[Pytree] = None) -> Pytree:
    """Restore into the structure of `template`.

    shardings: optional pytree of jax.sharding.Sharding, matched to
    template leaves BY KEYPATH (missing entries raise) — each device
    reads exactly the slices it owns, resharding on restore. Without
    shardings, leaves come back as host numpy arrays."""
    import jax

    by_name = _merged_index(path)
    data_dir = os.path.join(path, "data")

    sharding_by_name: Optional[Dict[str, Any]] = None
    if shardings is not None:
        sharding_by_name = dict(_leaf_paths(shardings))

    flat_t, treedef = jax.tree_util.tree_flatten_with_path(template)
    out = []
    for keypath, _leaf in flat_t:
        name = "/".join(_key_str(k) for k in keypath)
        entry = by_name.get(name)
        if entry is None:
            raise KeyError(f"leaf {name!r} not in checkpoint")
        shape = tuple(entry["shape"])
        if sharding_by_name is not None:
            sharding = sharding_by_name.get(name)
            if sharding is None:
                raise KeyError(
                    f"shardings pytree has no entry for leaf {name!r}")
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, e=entry: _read_region(data_dir, e, idx))
            out.append(arr)
        else:
            out.append(_read_region(
                data_dir, entry, tuple(slice(0, d) for d in shape)))
    return jax.tree_util.tree_unflatten(treedef, out)
