"""Sharded jax.Array checkpointing — orbax/tensorstore-style layout.

Reference role: python/ray/train checkpoints hold torch state dicts; the
TPU-native equivalent must persist GSPMD-sharded arrays. Design:

- save: every host writes only its OWN addressable shards (no gather —
  checkpoint bandwidth scales with hosts), one .npy per shard plus a
  JSON index describing global shape/dtype and each shard's index
  slices.
- restore: `jax.make_array_from_callback` pulls exactly the slices each
  device needs, reading only the shard files that overlap — works
  across a DIFFERENT mesh/sharding than the one that saved (reshard on
  restore), and across single-host/multi-host boundaries.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

Pytree = Any

_INDEX = "array_index.json"


def _leaf_paths(tree: Pytree) -> List[Tuple[str, Any]]:
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for keypath, leaf in flat:
        name = "/".join(_key_str(k) for k in keypath)
        out.append((name, leaf))
    return out


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def _slices_to_json(index: Tuple[slice, ...], shape) -> List[List[int]]:
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_pytree(tree: Pytree, path: str,
                process_index: Optional[int] = None) -> None:
    """Write this process's addressable shards of every leaf.

    Multi-host: every process calls this with the same path (shared
    filesystem); shard files are keyed by device id so writers never
    collide. Process 0 writes the index."""
    import jax

    process_index = jax.process_index() if process_index is None \
        else process_index
    data_dir = os.path.join(path, "data")
    os.makedirs(data_dir, exist_ok=True)
    index: Dict[str, Any] = {"leaves": []}
    for name, leaf in _leaf_paths(tree):
        arr = leaf
        safe = name.replace("/", ".")
        dtype = getattr(arr, "dtype", None) or np.asarray(arr).dtype
        entry = {"name": name, "shape": list(np.shape(arr)),
                 "dtype": str(dtype), "shards": []}
        if hasattr(arr, "addressable_shards"):
            for shard in arr.addressable_shards:
                fname = f"{safe}.d{shard.device.id}.npy"
                np.save(os.path.join(data_dir, fname),
                        np.asarray(shard.data))
                entry["shards"].append({
                    "file": fname,
                    "index": _slices_to_json(shard.index, arr.shape),
                })
        else:
            fname = f"{safe}.host.npy"
            np.save(os.path.join(data_dir, fname), np.asarray(arr))
            entry["shards"].append({
                "file": fname,
                "index": _slices_to_json(
                    tuple(slice(0, d) for d in np.shape(arr)),
                    np.shape(arr)),
            })
        index["leaves"].append(entry)
    if process_index == 0:
        tmp = os.path.join(path, _INDEX + ".tmp")
        with open(tmp, "w") as f:
            json.dump(index, f)
        os.replace(tmp, os.path.join(path, _INDEX))


def _read_region(data_dir: str, entry: dict,
                 want: Tuple[slice, ...]) -> np.ndarray:
    """Assemble the requested region from overlapping shard files."""
    shape = entry["shape"]
    want_bounds = []
    for sl, dim in zip(want, shape):
        start = 0 if sl.start is None else sl.start
        stop = dim if sl.stop is None else sl.stop
        want_bounds.append((int(start), int(stop)))
    out_shape = [b - a for a, b in want_bounds]
    out = np.empty(out_shape, dtype=np.dtype(entry["dtype"]))
    filled = 0
    for shard in entry["shards"]:
        bounds = shard["index"]
        # Overlap per dim.
        inter = []
        ok = True
        for (wa, wb), (sa, sb) in zip(want_bounds, bounds):
            a, b = max(wa, sa), min(wb, sb)
            if a >= b:
                ok = False
                break
            inter.append((a, b, sa, wa))
        if not ok:
            continue
        data = np.load(os.path.join(data_dir, shard["file"]))
        src = tuple(slice(a - sa, b - sa) for a, b, sa, _ in inter)
        dst = tuple(slice(a - wa, b - wa) for a, b, _, wa in inter)
        out[dst] = data[src]
        filled += int(np.prod([b - a for a, b, _, _ in inter]))
    if filled < int(np.prod(out_shape)):
        raise ValueError(
            f"checkpoint region {want_bounds} of {entry['name']} is "
            "incomplete (missing shard files — all hosts' shards must be "
            "visible at restore)")
    return out


def restore_pytree(template: Pytree, path: str,
                   shardings: Optional[Pytree] = None) -> Pytree:
    """Restore into the structure of `template`.

    shardings: optional matching pytree of jax.sharding.Sharding — each
    device reads exactly the slices it owns (resharding on restore).
    Without shardings, leaves come back as host numpy arrays."""
    import jax

    with open(os.path.join(path, _INDEX)) as f:
        index = json.load(f)
    by_name = {e["name"]: e for e in index["leaves"]}
    data_dir = os.path.join(path, "data")

    flat_t, treedef = jax.tree_util.tree_flatten_with_path(template)
    flat_s = None
    if shardings is not None:
        flat_s = [s for _, s in _leaf_paths(shardings)]
    out = []
    for i, (keypath, _leaf) in enumerate(flat_t):
        name = "/".join(_key_str(k) for k in keypath)
        entry = by_name.get(name)
        if entry is None:
            raise KeyError(f"leaf {name!r} not in checkpoint")
        shape = tuple(entry["shape"])
        dtype = np.dtype(entry["dtype"])
        if flat_s is not None:
            sharding = flat_s[i]
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, e=entry: _read_region(data_dir, e, idx))
            out.append(arr)
        else:
            out.append(_read_region(
                data_dir, entry, tuple(slice(0, d) for d in shape)))
    return jax.tree_util.tree_unflatten(treedef, out)
