"""ray_tpu.train — distributed training library (the north-star library).

Parity map to the reference (python/ray/train/):
- JaxTrainer / DataParallelTrainer  <- torch/torch_trainer.py:11,
  data_parallel_trainer.py:25
- JaxConfig/_JaxBackend             <- torch/config.py:150 (_TorchBackend)
- report/get_checkpoint/get_context <- _internal/session.py:403,754
- Checkpoint                        <- _checkpoint.py:56
- ScalingConfig/RunConfig/...       <- ray.air.config (re-exported)
- huggingface (prepare_trainer, RayTrainReportCallback, flax_train_step)
                                    <- huggingface/transformers/
"""

from ray_tpu.air import (CheckpointConfig, FailureConfig, Result, RunConfig,
                         ScalingConfig)
from ray_tpu.train.array_checkpoint import restore_pytree, save_pytree
from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.data_parallel_trainer import (DataParallelTrainer,
                                                 JaxTrainer)
from ray_tpu.train.jax_backend import JaxConfig
from ray_tpu.train.predictor import (BatchPredictor, JaxPredictor,
                                     Predictor, SklearnPredictor)
from ray_tpu.train._internal.session import (get_checkpoint, get_context,
                                             report)

__all__ = [
    "Backend",
    "restore_pytree",
    "save_pytree",
    "BackendConfig",
    "BatchPredictor",
    "Checkpoint",
    "JaxPredictor",
    "Predictor",
    "SklearnPredictor",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "JaxConfig",
    "JaxTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "get_checkpoint",
    "get_context",
    "report",
]
