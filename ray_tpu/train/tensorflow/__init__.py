"""TensorflowTrainer — TF_CONFIG distributed Keras on the WorkerGroup.

Reference: python/ray/train/tensorflow/config.py (`TensorflowConfig`,
`_setup_tensorflow_environment`: every worker gets a TF_CONFIG env var
naming the full worker cluster + its own task index, which
`tf.distribute.MultiWorkerMirroredStrategy` reads at construction) and
python/ray/train/tensorflow/tensorflow_trainer.py:25 (`TensorflowTrainer`).
Keras report callback analog of python/ray/train/tensorflow/keras.py
(`ReportCheckpointCallback`).

TPU-first note: this trainer exists for CPU/host-side TF workloads and
API parity (reference users bring `train_loop_per_worker` unchanged).
The TPU compute path is JaxTrainer/GSPMD — TF-on-TPU is deliberately not
wired (one compiler stack on the chips: XLA via JAX).

Keras 3 (bundled with TF >= 2.16) removed `model.fit` support under
MultiWorkerMirroredStrategy: multi-worker loops must use
`strategy.run` + `strategy.experimental_distribute_dataset` (the custom
training loop in tests/test_tensorflow_trainer.py is the template).
`ReportCheckpointCallback` remains for single-worker `model.fit`.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.data_parallel_trainer import DataParallelTrainer

__all__ = [
    "TensorflowConfig",
    "TensorflowTrainer",
    "prepare_dataset_shard",
    "ReportCheckpointCallback",
]


@dataclasses.dataclass
class TensorflowConfig(BackendConfig):
    @property
    def backend_cls(self):
        return _TensorflowBackend


def _set_tf_config(cluster_workers: List[str], index: int) -> None:
    """Runs inside each train worker BEFORE the user loop imports TF."""
    os.environ["TF_CONFIG"] = json.dumps({
        "cluster": {"worker": cluster_workers},
        "task": {"type": "worker", "index": index},
    })
    # Workers are CPU hosts here; keep TF off any tunneled accelerator.
    os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")


class _TensorflowBackend(Backend):
    def on_start(self, worker_group, backend_config: TensorflowConfig):
        if len(worker_group) <= 1:
            return
        import ray_tpu

        infos = worker_group.execute("get_node_info")
        cluster = [f"{i['ip']}:{i['free_port']}" for i in infos]
        ray_tpu.get([
            w.run_fn.remote(_set_tf_config, cluster, rank)
            for rank, w in enumerate(worker_group.workers)
        ])


class TensorflowTrainer(DataParallelTrainer):
    def __init__(self, train_loop_per_worker, *,
                 tensorflow_config: Optional[TensorflowConfig] = None,
                 **kwargs):
        super().__init__(train_loop_per_worker,
                         backend_config=tensorflow_config
                         or TensorflowConfig(),
                         **kwargs)


def prepare_dataset_shard(tf_dataset_shard):
    """Disable auto-sharding on a per-worker tf.data pipeline (the shard
    is already per-worker; reference train/tensorflow/train_loop_utils.py).
    """
    import tensorflow as tf

    options = tf.data.Options()
    options.experimental_distribute.auto_shard_policy = (
        tf.data.experimental.AutoShardPolicy.OFF)
    return tf_dataset_shard.with_options(options)


def ReportCheckpointCallback(checkpoint_on: Optional[str] = "epoch_end",
                             metrics: Optional[List[str]] = None):
    """Keras callback: stream epoch logs (and optionally a weights
    checkpoint) through `train.report`. Factory instead of a module-level
    class so `import ray_tpu.train.tensorflow` stays TF-free.

    checkpoint_on: "epoch_end" (every epoch), "train_end" (once, at the
    end), or None (metrics only).
    """
    import tensorflow as tf

    from ray_tpu import train
    from ray_tpu.train._internal.snapshots import RotatingSnapshots

    if checkpoint_on not in ("epoch_end", "train_end", None):
        raise ValueError(
            f"checkpoint_on={checkpoint_on!r}: expected 'epoch_end', "
            "'train_end', or None")

    class _Callback(tf.keras.callbacks.Callback):
        # Reports are queued and persisted asynchronously by the driver
        # poll, so snapshot dirs rotate (RotatingSnapshots) instead of
        # being deleted inline.
        def __init__(self):
            super().__init__()
            self._snapshots = RotatingSnapshots()

        def _save_checkpoint(self):
            if train.get_context().get_world_rank() != 0:
                return None
            d = self._snapshots.make("keras_ckpt_")
            # Keras 3 requires the .weights.h5 suffix.
            self.model.save_weights(
                os.path.join(d, "model.weights.h5"))
            return train.Checkpoint.from_directory(d)

        def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None):
            logs = dict(logs or {})
            out = ({k: logs[k] for k in metrics if k in logs}
                   if metrics else logs)
            out["epoch"] = epoch
            ckpt = (self._save_checkpoint()
                    if checkpoint_on == "epoch_end" else None)
            train.report(out, checkpoint=ckpt)

        def on_train_end(self, logs: Optional[Dict] = None):
            if checkpoint_on == "train_end":
                train.report({"train_end": True},
                             checkpoint=self._save_checkpoint())

    return _Callback()
