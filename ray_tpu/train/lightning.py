"""PyTorch Lightning integration (gated).

Reference: python/ray/train/lightning/ — `prepare_trainer`,
`RayDDPStrategy`, `RayLightningEnvironment`, `RayTrainReportCallback`:
Lightning's trainer runs inside a TorchTrainer worker, discovers the
Ray-provided process group/world instead of launching its own, and
streams epoch metrics + checkpoints through `train.report`.

Lightning is an optional dependency (not in this image): importing this
module always works; each factory raises an informative ImportError
without it. With it, the returned objects plug into
`lightning.Trainer(strategy=RayDDPStrategy(), plugins=[
RayLightningEnvironment()], callbacks=[RayTrainReportCallback()])`
inside a `TorchTrainer` train loop.
"""

from __future__ import annotations

import os

__all__ = [
    "prepare_trainer",
    "RayDDPStrategy",
    "RayLightningEnvironment",
    "RayTrainReportCallback",
]

_INSTALL_MSG = (
    "requires the 'lightning' (or 'pytorch_lightning') package, which is "
    "not installed in this environment; TorchTrainer runs plain torch "
    "loops without it, and the TPU path is JaxTrainer")


def _import_lightning():
    try:
        import lightning.pytorch as pl
        return pl
    except ImportError:
        pass
    try:
        import pytorch_lightning as pl
        return pl
    except ImportError as e:
        raise ImportError(f"ray_tpu.train.lightning {_INSTALL_MSG}") from e


def RayDDPStrategy(**kwargs):
    """DDP strategy that joins the process group the TorchTrainer
    backend already created instead of spawning its own launcher
    (reference: train/lightning/_lightning_utils.py RayDDPStrategy)."""
    _import_lightning()
    try:
        from lightning.pytorch.strategies import DDPStrategy
    except ImportError:
        from pytorch_lightning.strategies import DDPStrategy

    class _RayDDPStrategy(DDPStrategy):
        def setup_environment(self):
            # torch.distributed is already initialized by _TorchBackend;
            # Lightning must not re-init or tear it down.
            import torch.distributed as dist

            assert dist.is_initialized(), \
                "RayDDPStrategy requires a live TorchTrainer process group"
            super().setup_environment()

    kwargs.setdefault("process_group_backend", "gloo")
    return _RayDDPStrategy(**kwargs)


def RayLightningEnvironment():
    """ClusterEnvironment describing the TorchTrainer worker gang
    (reference: RayLightningEnvironment)."""
    pl = _import_lightning()  # noqa: F841  (gate)
    try:
        from lightning.pytorch.plugins.environments import (
            LightningEnvironment)
    except ImportError:
        from pytorch_lightning.plugins.environments import (
            LightningEnvironment)

    from ray_tpu import train

    class _RayEnv(LightningEnvironment):
        def world_size(self) -> int:
            return train.get_context().get_world_size()

        def global_rank(self) -> int:
            return train.get_context().get_world_rank()

        def local_rank(self) -> int:
            return train.get_context().get_local_rank()

        @property
        def creates_processes_externally(self) -> bool:
            return True  # the WorkerGroup did

    return _RayEnv()


def RayTrainReportCallback(checkpoint_every_n_epochs: int = 1):
    """Stream Lightning's logged metrics + a checkpoint through
    train.report at epoch end (reference: RayTrainReportCallback)."""
    pl = _import_lightning()

    from ray_tpu import train

    from ray_tpu.train._internal.snapshots import RotatingSnapshots

    class _Callback(pl.Callback):
        def __init__(self):
            super().__init__()
            self._snapshots = RotatingSnapshots()

        def on_train_epoch_end(self, trainer, pl_module):
            metrics = {k: float(v) for k, v in
                       trainer.callback_metrics.items()}
            metrics["epoch"] = trainer.current_epoch
            ckpt = None
            if train.get_context().get_world_rank() == 0 and \
                    trainer.current_epoch % checkpoint_every_n_epochs == 0:
                d = self._snapshots.make("lightning_ckpt_")
                trainer.save_checkpoint(
                    os.path.join(d, "checkpoint.ckpt"))
                ckpt = train.Checkpoint.from_directory(d)
            train.report(metrics, checkpoint=ckpt)

    return _Callback()


def prepare_trainer(trainer):
    """Validate a lightning.Trainer for running under TorchTrainer
    (reference: train/lightning/prepare_trainer)."""
    _import_lightning()
    return trainer
