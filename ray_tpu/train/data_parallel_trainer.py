"""DataParallelTrainer / JaxTrainer.

Reference: python/ray/train/data_parallel_trainer.py:25 +
base_trainer.py:111 (`fit` :567). Differences from the reference:
`fit()` drives the run directly (a Tune wrapper is layered on from
ray_tpu.tune instead of the reverse), and the default backend is JAX —
SPMD over a TPU mesh — rather than torch DDP.

Failure semantics (SURVEY.md §5.3): restart-from-checkpoint. Any worker
failure tears down the WHOLE gang (a dead host invalidates the ICI mesh)
and restarts it from the latest persisted checkpoint, up to
FailureConfig.max_failures times.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu.air import (CheckpointConfig, FailureConfig, Result, RunConfig,
                         ScalingConfig)
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.jax_backend import JaxConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train._internal.backend_executor import BackendExecutor
from ray_tpu.train._internal.storage import StorageContext


class DataParallelTrainer:
    _default_backend_config: BackendConfig = None  # set per subclass

    def __init__(self,
                 train_loop_per_worker: Callable,
                 *,
                 train_loop_config: Optional[Dict[str, Any]] = None,
                 backend_config: Optional[BackendConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None,
                 datasets: Optional[Dict[str, Any]] = None):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or \
            (self._default_backend_config or BackendConfig())
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint
        self.datasets = datasets or {}

    # ------------------------------------------------------------------
    def fit(self) -> Result:
        run_name = self.run_config.name or f"train_{uuid.uuid4().hex[:8]}"
        storage = StorageContext(
            self.run_config.resolved_storage_path(), run_name)
        failure_config = self.run_config.failure_config or FailureConfig()
        max_failures = failure_config.max_failures
        if max_failures < 0:
            max_failures = 10 ** 9

        attempt = 0
        last_error: Optional[BaseException] = None
        while True:
            checkpoint = storage.latest_checkpoint() or \
                self.resume_from_checkpoint
            try:
                return self._run_attempt(storage, run_name, checkpoint)
            except Exception as e:  # gang failure → restart from checkpoint
                last_error = e
                attempt += 1
                if attempt > max_failures:
                    # the failed attempt may have persisted newer checkpoints
                    return Result(metrics={},
                                  checkpoint=storage.latest_checkpoint()
                                  or checkpoint,
                                  error=last_error, path=storage.trial_dir)

    # ------------------------------------------------------------------
    def _run_attempt(self, storage: StorageContext, run_name: str,
                     checkpoint: Optional[Checkpoint]) -> Result:
        executor = BackendExecutor(self.backend_config, self.scaling_config)
        ckpt_config = self.run_config.checkpoint_config or CheckpointConfig()
        datasets = self.datasets

        train_fn = self.train_loop_per_worker
        config = dict(self.train_loop_config)
        if datasets:
            config["_datasets"] = datasets

        latest_checkpoint = checkpoint
        last_metrics: Dict[str, Any] = {}
        ckpt_index = 0
        if checkpoint is not None:
            # continue numbering after the restored checkpoint
            base = checkpoint.path.rstrip("/").rsplit("_", 1)[-1]
            ckpt_index = int(base) + 1 if base.isdigit() else 0
        # Rebuild retention state from disk so restarts keep pruning across
        # attempts (metrics were saved as checkpoint metadata at persist).
        checkpoints_with_metrics = [
            (c, c.get_metadata().get("metrics", {}))
            for c in storage.list_checkpoints()]

        try:
            executor.start()
            executor.start_training(
                train_fn, config, experiment_name=run_name,
                trial_name=run_name, trial_dir=storage.trial_dir,
                checkpoint=checkpoint)

            rank_reports = None  # per-rank FIFO of not-yet-aligned reports
            while True:
                rounds = executor.poll()
                if rank_reports is None:
                    rank_reports = [[] for _ in rounds]
                for rank, r in enumerate(rounds):
                    rank_reports[rank].extend(r["results"])
                done = [r["done"] for r in rounds]
                # Ranks report in lockstep (every worker calls report() the
                # same number of times — reference contract), so the i-th
                # report of each rank forms one logical result/checkpoint.
                # A report index is processed only once every rank has
                # delivered it (or finished) — regardless of which 50ms
                # poll round each rank's report arrived in. Checkpoints are
                # persisted BEFORE worker errors are raised so a restart
                # can resume from them.
                while any(rank_reports) and \
                        all(buf or d
                            for buf, d in zip(rank_reports, done)):
                    batch = [(rank, buf.pop(0))
                             for rank, buf in enumerate(rank_reports) if buf]
                    metrics_i = next(
                        (rep["metrics"] for rank, rep in batch if rank == 0),
                        batch[0][1]["metrics"])
                    ckpt_here = None
                    for rank, rep in batch:
                        if rep["checkpoint"]:
                            # rank 0 lands at the checkpoint root; other
                            # ranks under shard_rank_<k>/ so same-named
                            # files never clobber
                            persisted = storage.persist_checkpoint(
                                rep["checkpoint"], ckpt_index, rank=rank)
                            if rank == 0 or ckpt_here is None:
                                ckpt_here = persisted
                    last_metrics = metrics_i
                    if ckpt_here is not None:
                        latest_checkpoint = ckpt_here
                        ckpt_here.update_metadata({"metrics": metrics_i})
                        checkpoints_with_metrics.append(
                            (ckpt_here, metrics_i))
                        ckpt_index += 1
                        self._apply_retention(storage,
                                              checkpoints_with_metrics,
                                              ckpt_config,
                                              protect=latest_checkpoint)
                for err_rank, r in enumerate(rounds):
                    if r["error"]:
                        raise RuntimeError(
                            f"worker {err_rank} failed:\n{r['error']}")
                if all(done):
                    break
                time.sleep(0.05)
        finally:
            executor.shutdown()

        return Result(metrics=last_metrics, checkpoint=latest_checkpoint,
                      path=storage.trial_dir,
                      best_checkpoints=list(checkpoints_with_metrics))

    @staticmethod
    def _apply_retention(storage: StorageContext, ckpts, cfg, protect=None):
        """Keep top-K by score attr (reference CheckpointManager). The
        `protect` checkpoint (the latest) is never deleted — Result.
        checkpoint and restart-resume must stay valid even when the newest
        checkpoint scores worst."""
        import shutil

        if not cfg.num_to_keep or len(ckpts) <= cfg.num_to_keep:
            return
        attr = cfg.checkpoint_score_attribute

        def score(item):
            _, m = item
            if attr is None or attr not in m:
                return 0.0
            v = float(m[attr])
            return v if cfg.checkpoint_score_order == "max" else -v

        if attr is None:
            # keep most recent K
            doomed = ckpts[:-cfg.num_to_keep]
            keep = ckpts[-cfg.num_to_keep:]
        else:
            ranked = sorted(ckpts, key=score, reverse=True)
            keep, doomed = ranked[:cfg.num_to_keep], ranked[cfg.num_to_keep:]
        protected = [d for d in doomed
                     if protect is not None and d[0].path == protect.path]
        doomed = [d for d in doomed if d not in protected]
        for c, _ in doomed:
            shutil.rmtree(c.path, ignore_errors=True)
        ckpts[:] = keep + protected


class JaxTrainer(DataParallelTrainer):
    """The flagship trainer: JAX SPMD workers on TPU hosts.

    North star of the whole build (BASELINE.json): analog of
    TorchTrainer (python/ray/train/torch/torch_trainer.py:11) with
    GSPMD/ICI in place of DDP/NCCL.
    """

    _default_backend_config = None

    def __init__(self, train_loop_per_worker, *, jax_config=None, **kwargs):
        backend_config = jax_config or JaxConfig()
        super().__init__(train_loop_per_worker,
                         backend_config=backend_config, **kwargs)
