"""HuggingFace Transformers integration.

Reference: python/ray/train/huggingface/transformers/ —
``RayTrainReportCallback`` (bridges transformers.Trainer logs/saves into
ray.train.report) and ``prepare_trainer`` (injects the callback +
distributed plumbing). TPU-native differences:

- The torch path is unchanged in spirit: a ``transformers.TrainerCallback``
  that forwards each HF log to :func:`ray_tpu.train.report`, attaching the
  just-saved HF checkpoint directory as a ray_tpu Checkpoint. Runs under
  :class:`ray_tpu.train.torch.TorchTrainer` (gloo/CPU here).
- The flagship path is Flax-on-TPU: ``flax_train_step`` builds a jitted
  GSPMD train step for any HF Flax model (``Flax*ForCausalLM`` etc.)
  directly from ``model.__call__`` — no DDP/accelerate wrapper layer, the
  mesh sharding IS the distribution strategy. Run it inside a
  ``JaxTrainer`` train loop.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional


def _transformers():
    import transformers

    return transformers


# --------------------------------------------------------------------------
# torch Trainer bridge (reference: RayTrainReportCallback)
# --------------------------------------------------------------------------

def RayTrainReportCallback():
    """Build the transformers→ray_tpu reporting callback.

    Factory (not a module-level class) so importing this module never
    hard-requires transformers. Each ``on_log`` reports the HF metrics;
    if a checkpoint was saved since the last report it ships with the
    metrics (reference: _transformers_utils.py RayTrainReportCallback —
    same save-then-report ordering so the checkpoint matches the step).
    """
    transformers = _transformers()

    from ray_tpu import train

    from ray_tpu.train._internal.snapshots import RotatingSnapshots

    class _Callback(transformers.TrainerCallback):
        def __init__(self):
            self._pending_ckpt_dir: Optional[str] = None
            # Bounded snapshot retention (see RotatingSnapshots: the
            # bound exceeds the session's undrained-report depth).
            self._snapshots = RotatingSnapshots()

        def on_save(self, args, state, control, **kwargs):
            # Snapshot the HF checkpoint into a private dir NOW:
            # save_total_limit rotation may delete the original before
            # the (queued) report is persisted by the driver, and a
            # by-reference path would then fail the whole run.
            import shutil

            src = os.path.join(args.output_dir,
                               f"checkpoint-{state.global_step}")
            if os.path.isdir(src):
                dst = self._snapshots.make("ray_tpu_hf_ckpt_")
                snap = os.path.join(dst, os.path.basename(src))
                shutil.copytree(src, snap)
                self._pending_ckpt_dir = snap
            return control

        def on_log(self, args, state, control, logs=None, **kwargs):
            metrics = dict(logs or {})
            metrics["step"] = state.global_step
            metrics["epoch"] = float(state.epoch or 0)
            ckpt = None
            if self._pending_ckpt_dir and \
                    os.path.isdir(self._pending_ckpt_dir):
                ckpt = train.Checkpoint(self._pending_ckpt_dir)
                self._pending_ckpt_dir = None
            train.report(metrics, checkpoint=ckpt)
            return control

    return _Callback()


def prepare_trainer(trainer):
    """Attach the ray_tpu reporting callback to a transformers.Trainer
    (idempotent). Reference: huggingface/transformers/prepare_trainer."""
    transformers = _transformers()
    has_ours = any(
        type(cb).__name__ == "_Callback" and
        type(cb).__qualname__.startswith("RayTrainReportCallback")
        for cb in trainer.callback_handler.callbacks)
    if not has_ours:
        trainer.add_callback(RayTrainReportCallback())
    # transformers' own printing is redundant under a train session.
    trainer.remove_callback(transformers.PrinterCallback)
    return trainer


# --------------------------------------------------------------------------
# Flax-on-TPU path (flagship): jitted GSPMD step for any HF Flax model
# --------------------------------------------------------------------------

def flax_causal_lm_loss(model) -> Callable:
    """Next-token cross-entropy loss closed over an HF Flax causal-LM.

    Works with any ``Flax*ForCausalLM``/``Flax*LMHeadModel``: the batch is
    ``{"input_ids": [B, S+1]}``; logits come from ``model(inputs,
    params=params, train=False)`` — the functional entry point every
    FlaxPreTrainedModel exposes. NOTE: the step is deterministic —
    dropout is DISABLED (train=False; the fixed loss_fn(params, batch)
    signature carries no rng). Zero the *_pdrop fields in the config if
    you need parity with a dropout-regularized HF Trainer run."""
    import jax.numpy as jnp
    import optax

    def loss_fn(params, batch):
        tokens = batch["input_ids"]
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        # model.__call__ with an explicit params= override is the
        # functional entry point every FlaxPreTrainedModel exposes
        # (handles attention_mask/position_id defaults per arch).
        out = model(inputs, params=params, train=False)
        logits = out.logits if hasattr(out, "logits") else out[0]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets).mean()

    return loss_fn


def flax_train_step(model, optimizer, mesh=None,
                    param_specs: Any = None,
                    loss_fn: Optional[Callable] = None):
    """(init_fn, step_fn) for fine-tuning an HF Flax model under GSPMD.

    Defaults: fully-replicated params on a 1-axis dp mesh of all visible
    devices — pass a mesh + param_specs for fsdp/tp layouts. The step is
    the same donated, jitted train step the native models use
    (ray_tpu.models.training.make_sharded_train_step), so HF models get
    the identical TPU execution path."""
    import jax

    from ray_tpu.models.training import make_sharded_train_step
    from ray_tpu.parallel import create_mesh

    if mesh is None:
        mesh = create_mesh({"dp": len(jax.devices())}, jax.devices())
    if param_specs is None:
        from jax.sharding import PartitionSpec

        param_specs = jax.tree_util.tree_map(
            lambda _: PartitionSpec(), model.params)
    return make_sharded_train_step(
        loss_fn or flax_causal_lm_loss(model), optimizer, mesh,
        param_specs)


def save_flax_checkpoint(model, params, directory: str) -> str:
    """Persist HF config + params as a reloadable directory checkpoint."""
    os.makedirs(directory, exist_ok=True)
    model.config.save_pretrained(directory)
    from ray_tpu.train.array_checkpoint import save_pytree

    save_pytree(params, os.path.join(directory, "flax_params"))
    return directory


def load_flax_checkpoint(model_cls, directory: str):
    """Rebuild (model, params) from :func:`save_flax_checkpoint`."""
    transformers = _transformers()
    config = transformers.AutoConfig.from_pretrained(directory)
    model = model_cls(config, seed=0)
    from ray_tpu.train.array_checkpoint import restore_pytree

    params = restore_pytree(model.params,
                            os.path.join(directory, "flax_params"))
    # Bind the restored weights as the model's own: bare model(inputs)
    # (the normal HF calling convention) must NOT silently run the
    # constructor's random init.
    model.params = params
    return model, params
