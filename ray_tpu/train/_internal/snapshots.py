"""Rotating checkpoint-snapshot dirs for report callbacks.

`train.report(checkpoint=...)` is queued and persisted asynchronously by
the driver's poll loop, so a callback must not delete a snapshot dir
inline after reporting — instead it keeps a bounded FIFO of snapshot
dirs and prunes the oldest once the bound is exceeded. The bound must
EXCEED the session's undrained-report queue depth (_TrainSession
Semaphore(8)): a still-queued checkpoint's dir must never be pruned
before the driver copies it. Shared by the TF/Lightning/HF report
callbacks.
"""

from __future__ import annotations

import shutil
import tempfile
from typing import List


class RotatingSnapshots:
    def __init__(self, max_snapshots: int = 9):
        self._dirs: List[str] = []
        self._max = max_snapshots

    def make(self, prefix: str) -> str:
        """Create and track a fresh snapshot dir."""
        return self.track(tempfile.mkdtemp(prefix=prefix))

    def track(self, path: str) -> str:
        """Track an externally created dir; prune oldest beyond the
        bound."""
        self._dirs.append(path)
        while len(self._dirs) > self._max:
            shutil.rmtree(self._dirs.pop(0), ignore_errors=True)
        return path
