"""Per-worker train session.

Reference: python/ray/train/_internal/session.py:111 (`_TrainSession`) —
runs the user loop in a RunnerThread; `report()` (:403,667) enqueues
(metrics, checkpoint) for the driver-side executor to poll;
`get_checkpoint()` (:754) hands the restore checkpoint to the user loop.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclasses.dataclass
class TrainContext:
    world_size: int = 1
    world_rank: int = 0
    local_rank: int = 0
    node_rank: int = 0
    experiment_name: str = ""
    trial_name: str = ""
    trial_dir: str = ""

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_experiment_name(self) -> str:
        return self.experiment_name

    def get_trial_name(self) -> str:
        return self.trial_name


class _TrainSession:
    def __init__(self, train_fn: Callable[[], None], context: TrainContext,
                 checkpoint: Optional[Checkpoint] = None):
        self.context = context
        self.checkpoint = checkpoint
        self.result_queue: "queue.Queue" = queue.Queue()
        self.done = threading.Event()
        self.error: Optional[str] = None
        self._thread = threading.Thread(
            target=self._run, args=(train_fn,), daemon=True)
        # Backpressure: the user loop blocks in report() until the driver
        # drains, bounding in-flight results (reference uses the same
        # queue-handshake in session.py:212).
        self._continue = threading.Semaphore(8)

    def start(self):
        self._thread.start()

    def _run(self, train_fn):
        try:
            train_fn()
        except BaseException:
            self.error = traceback.format_exc()
        finally:
            self.done.set()

    # ---- called from the user loop (worker thread) ----
    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None):
        self._continue.acquire()
        self.result_queue.put({"metrics": dict(metrics),
                               "checkpoint": checkpoint})

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint

    # ---- called by the worker actor (poll RPC) ----
    def poll(self):
        out = []
        while True:
            try:
                out.append(self.result_queue.get_nowait())
                self._continue.release()
            except queue.Empty:
                break
        return {
            "results": out,
            "done": self.done.is_set(),
            "error": self.error,
        }

    def join(self, timeout: Optional[float] = None) -> bool:
        self._thread.join(timeout)
        return not self._thread.is_alive()


# ---------------------------------------------------------------------------
# Module-level API surfaced as ray_tpu.train.report / get_checkpoint /
# get_context (modern reference API: python/ray/train/_internal/session.py
# module functions).
# ---------------------------------------------------------------------------

_session: Optional[_TrainSession] = None


def _set_session(s: Optional[_TrainSession]):
    global _session
    _session = s


def get_session() -> Optional[_TrainSession]:
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    if _session is None:
        raise RuntimeError(
            "ray_tpu.train.report() called outside a train session")
    _session.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    if _session is None:
        return None
    return _session.get_checkpoint()


def get_context() -> TrainContext:
    if _session is None:
        return TrainContext()
    return _session.context
