"""StorageContext — resolves run/trial/checkpoint paths and persists
checkpoints (reference python/ray/train/_internal/storage.py:352).

Filesystem only (local, NFS, gcsfuse mounts); remote object stores can be
added behind the same interface later.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint


class StorageContext:
    def __init__(self, storage_path: str, experiment_name: str,
                 trial_name: Optional[str] = None):
        self.storage_path = os.path.abspath(storage_path)
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        os.makedirs(self.trial_dir, exist_ok=True)

    @property
    def experiment_dir(self) -> str:
        return os.path.join(self.storage_path, self.experiment_name)

    @property
    def trial_dir(self) -> str:
        if self.trial_name is None:
            return self.experiment_dir
        return os.path.join(self.experiment_dir, self.trial_name)

    def checkpoint_dir(self, index: int) -> str:
        return os.path.join(self.trial_dir, f"checkpoint_{index:06d}")

    def persist_checkpoint(self, checkpoint: Checkpoint, index: int,
                           rank: int = 0) -> Checkpoint:
        """Copy a worker-local checkpoint dir into durable storage.

        Rank 0's files land at the checkpoint root; other ranks' under
        shard_rank_<k>/ so same-named per-rank files never clobber each
        other (multi-host GSPMD shard layout)."""
        root = self.checkpoint_dir(index)
        dest = root if rank == 0 else os.path.join(root,
                                                   f"shard_rank_{rank}")
        if os.path.abspath(checkpoint.path) == dest:
            return Checkpoint(root)
        os.makedirs(dest, exist_ok=True)
        shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
        return Checkpoint(root)

    def list_checkpoints(self) -> list:
        if not os.path.isdir(self.trial_dir):
            return []
        return [Checkpoint(os.path.join(self.trial_dir, d))
                for d in sorted(os.listdir(self.trial_dir))
                if d.startswith("checkpoint_")]

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        cks = self.list_checkpoints()
        return cks[-1] if cks else None


def make_experiment_name(prefix: str = "train") -> str:
    return f"{prefix}_{time.strftime('%Y%m%d_%H%M%S')}"
