"""WorkerGroup — a gang of train-worker actors.

Reference: python/ray/train/_internal/worker_group.py:102 (`WorkerGroup`,
`start` :193). TPU-first difference: when the ScalingConfig names a slice
topology, the gang is placed via `slice_placement_group` (all hosts of the
slice leased atomically) instead of independent bundles.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.air import ScalingConfig
from ray_tpu.core.placement_group import (placement_group,
                                          remove_placement_group,
                                          slice_placement_group)


class RayTrainWorker:
    """Actor hosting one train process (one TPU host's worth of chips)."""

    def __init__(self, world_rank: int):
        self.world_rank = world_rank
        self.session = None

    def get_node_info(self) -> Dict[str, Any]:
        hostname = socket.gethostname()
        try:
            ip = socket.gethostbyname(hostname)
        except socket.gaierror:
            ip = "127.0.0.1"
        with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        return {"hostname": hostname, "ip": ip, "free_port": port,
                "pid": os.getpid()}

    def set_env(self, env: Dict[str, str]) -> None:
        os.environ.update(env)

    def run_fn(self, fn: Callable, *args, **kwargs):
        """Execute an arbitrary setup fn in the worker (backend hooks)."""
        return fn(*args, **kwargs)

    def start_session(self, train_fn: Callable[[], None], context,
                      checkpoint=None) -> None:
        from ray_tpu.train._internal import session as session_mod
        from ray_tpu.train._internal.session import _TrainSession

        self.session = _TrainSession(train_fn, context, checkpoint)
        session_mod._set_session(self.session)
        self.session.start()

    def poll(self) -> Dict[str, Any]:
        if self.session is None:
            return {"results": [], "done": True, "error": None}
        return self.session.poll()

    def join(self, timeout: Optional[float] = None) -> bool:
        if self.session is None:
            return True
        ok = self.session.join(timeout)
        from ray_tpu.train._internal import session as session_mod

        if ok:
            session_mod._set_session(None)
        return ok


class WorkerGroup:
    def __init__(self, scaling_config: ScalingConfig):
        self.scaling_config = scaling_config
        self.workers: List[Any] = []
        self._pg = None

    def start(self) -> None:
        sc = self.scaling_config
        if sc.use_tpu and sc.topology:
            self._pg = slice_placement_group(
                num_hosts=sc.num_workers,
                chips_per_host=sc.chips_per_worker)
        else:
            self._pg = placement_group(
                [sc.bundle() for _ in range(sc.num_workers)],
                strategy=sc.placement_strategy)
        self._pg.ready()
        actor_cls = ray_tpu.remote(RayTrainWorker)
        self.workers = [
            actor_cls.options(
                placement_group=self._pg,
                placement_group_bundle_index=i,
                num_cpus=1,
                resources={k: v for k, v in sc.bundle().items()
                           if k not in ("CPU",)},
            ).remote(i)
            for i in range(sc.num_workers)
        ]
        # Barrier: all actors constructed and reachable.
        ray_tpu.get([w.get_node_info.remote() for w in self.workers])

    def execute(self, method: str, *args, **kwargs) -> List[Any]:
        """Call a worker method on ALL workers, gather results."""
        return ray_tpu.get(
            [getattr(w, method).remote(*args, **kwargs)
             for w in self.workers])

    def execute_single(self, rank: int, method: str, *args, **kwargs):
        return ray_tpu.get(
            getattr(self.workers[rank], method).remote(*args, **kwargs))

    def __len__(self) -> int:
        return len(self.workers)

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
