"""BackendExecutor — drives the worker gang through a training run.

Reference: python/ray/train/_internal/backend_executor.py:67 (`start`
:129 creates the WorkerGroup + backend.on_start; `start_training` :445
launches the session on every worker; the trainer then polls results).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.air import ScalingConfig
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train._internal.session import TrainContext
from ray_tpu.train._internal.worker_group import WorkerGroup


class TrainingWorkerError(RuntimeError):
    pass


def _session_entrypoint(train_fn, config):
    return functools.partial(train_fn, config) if _takes_arg(train_fn) \
        else train_fn


def _takes_arg(fn) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len(sig.parameters) >= 1


class BackendExecutor:
    def __init__(self, backend_config: BackendConfig,
                 scaling_config: ScalingConfig):
        self.backend_config = backend_config
        self.scaling_config = scaling_config
        self.backend = backend_config.backend_cls()
        self.worker_group: Optional[WorkerGroup] = None

    def start(self) -> None:
        self.worker_group = WorkerGroup(self.scaling_config)
        self.worker_group.start()
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       experiment_name: str, trial_name: str, trial_dir: str,
                       checkpoint: Optional[Checkpoint] = None) -> None:
        assert self.worker_group is not None, "call start() first"
        self.backend.on_training_start(self.worker_group, self.backend_config)
        n = len(self.worker_group)
        entry = _session_entrypoint(train_fn, config)
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            ctx = TrainContext(
                world_size=n, world_rank=rank, local_rank=0, node_rank=rank,
                experiment_name=experiment_name, trial_name=trial_name,
                trial_dir=trial_dir)
            refs.append(w.start_session.remote(entry, ctx, checkpoint))
        import ray_tpu

        ray_tpu.get(refs)

    def poll(self) -> List[Dict[str, Any]]:
        """One poll round: per-worker {results, done, error}."""
        return self.worker_group.execute("poll")

    def shutdown(self) -> None:
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group,
                                         self.backend_config)
            except Exception:
                pass
            self.worker_group.shutdown()
            self.worker_group = None
